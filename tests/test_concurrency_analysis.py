"""The concurrency soundness plane (trino_tpu/analysis/).

Three layers under test:

* the static analyzer — deliberately broken in-memory fixture modules
  must each produce the right typed finding at the right file:line, and
  the committed package must produce none;
* the runtime lock witness — order violations and non-reentrant
  re-entry raise typed LockOrderError naming both locks and both sites;
* the thread registry — named ownership, leak reporting, join_all.

Plus the regression tests for the races the analyzer surfaced in the
seed tree (LAST_RUN_INFO, MESH_COUNTERS, _GLOBAL_FN_CACHE).
"""

import threading
import time

import pytest

from trino_tpu.analysis import analyze_package, analyze_sources
from trino_tpu.analysis.witness import (
    LockOrderError,
    named_condition,
    named_lock,
    named_rlock,
    reset_witness_for_tests,
    seed_order,
    violation_count,
)
from trino_tpu.analysis import threadreg


@pytest.fixture(autouse=True)
def _fresh_witness():
    """Tests here deliberately trip the witness; reset its order graph
    and violation counter around each one so the module-scoped
    sanitizer fixture (conftest) sees a clean slate afterwards."""
    reset_witness_for_tests()
    yield
    reset_witness_for_tests()


# -- static analyzer: broken fixtures ---------------------------------

CYCLE_SRC = """\
from trino_tpu.analysis.witness import named_lock

_lock_a = named_lock("fix._lock_a")
_lock_b = named_lock("fix._lock_b")


def forward():
    with _lock_a:
        with _lock_b:
            pass


def backward():
    with _lock_b:
        with _lock_a:
            pass
"""


def test_static_lock_order_cycle_reported_with_both_paths():
    rep = analyze_sources({"fix": ("fix.py", CYCLE_SRC)})
    cycles = [f for f in rep.findings if f.kind == "lock-cycle"]
    assert len(cycles) == 1
    f = cycles[0]
    assert f.file == "fix.py"
    # both lock ids and both witness sites must appear in the report
    assert "fix._lock_a" in f.message and "fix._lock_b" in f.message
    assert "fix.py:9" in f.message  # forward's inner acquire
    assert "fix.py:15" in f.message  # backward's inner acquire


def test_static_cycle_through_call_edge():
    # the cycle closes through a function call, not a nested with:
    # holder_a holds A and calls helper, which takes B; holder_b does
    # the reverse. Neither function nests both locks syntactically.
    src = """\
from trino_tpu.analysis.witness import named_lock

_a = named_lock("m._a")
_b = named_lock("m._b")


def take_b():
    with _b:
        pass


def take_a():
    with _a:
        pass


def holder_a():
    with _a:
        take_b()


def holder_b():
    with _b:
        take_a()
"""
    rep = analyze_sources({"m": ("m.py", src)})
    cycles = [f for f in rep.findings if f.kind == "lock-cycle"]
    assert len(cycles) == 1
    assert "m._a" in cycles[0].message and "m._b" in cycles[0].message


BARE_WRITE_SRC = """\
from trino_tpu.analysis.witness import named_lock

_cache_lock = named_lock("bw._cache_lock")
CACHE = {}  # guarded_by: _cache_lock


def good(key, value):
    with _cache_lock:
        CACHE[key] = value


def bad(key, value):
    CACHE[key] = value
"""


def test_static_bare_guarded_write_flagged_at_line():
    rep = analyze_sources({"bw": ("bw.py", BARE_WRITE_SRC)})
    hits = [f for f in rep.findings if f.kind == "guarded-field"]
    assert len(hits) == 1
    assert hits[0].file == "bw.py"
    assert hits[0].line == 13  # the write inside bad(), not good()
    assert "_cache_lock" in hits[0].message


UNLOCKED_GLOBAL_SRC = """\
REGISTRY = {}


def record(key, value):
    REGISTRY[key] = value
"""


def test_static_unlocked_mutable_global_write_flagged():
    rep = analyze_sources({"ug": ("ug.py", UNLOCKED_GLOBAL_SRC)})
    hits = [f for f in rep.findings if f.kind == "unlocked-global-write"]
    assert len(hits) == 1
    assert hits[0].file == "ug.py" and hits[0].line == 5


LEAKED_THREAD_SRC = """\
import threading


def spawn_worker(target):
    t = threading.Thread(target=target)
    t.start()
    return t
"""


def test_static_raw_thread_spawn_flagged():
    rep = analyze_sources({"lt": ("lt.py", LEAKED_THREAD_SRC)})
    hits = [f for f in rep.findings if f.kind == "unregistered-thread"]
    assert len(hits) == 1
    assert hits[0].file == "lt.py" and hits[0].line == 5


REENTRY_SRC = """\
from trino_tpu.analysis.witness import named_lock

_mu = named_lock("re._mu")


def recurse():
    with _mu:
        with _mu:
            pass
"""


def test_static_nonreentrant_reentry_flagged():
    rep = analyze_sources({"re_fix": ("re_fix.py", REENTRY_SRC)})
    hits = [f for f in rep.findings if f.kind == "lock-reentry"]
    assert len(hits) == 1
    assert hits[0].line == 8


WAIT_HOLDING_SRC = """\
from trino_tpu.analysis.witness import named_condition, named_lock

_outer = named_lock("wh._outer")
_cv = named_condition("wh._cv")


def stall():
    with _outer:
        with _cv:
            _cv.wait()
"""


def test_static_wait_while_holding_flagged():
    rep = analyze_sources({"wh": ("wh.py", WAIT_HOLDING_SRC)})
    hits = [f for f in rep.findings if f.kind == "wait-while-holding"]
    assert len(hits) == 1
    assert "wh._outer" in hits[0].message


def test_full_package_is_clean():
    """The committed tree must analyze clean — same assertion as the
    bench.py --analyze CI gate."""
    rep = analyze_package()
    assert rep.files > 100
    assert len(rep.graph.locks) > 40
    assert rep.graph.sites > 200
    assert rep.ok, "\n".join(
        f"[{f.kind}] {f.file}:{f.line}: {f.message}" for f in rep.findings
    )


# -- runtime witness ---------------------------------------------------

def test_witness_order_violation_raises_typed_error():
    a = named_lock("t16.order_a")
    b = named_lock("t16.order_b")
    with a:
        with b:
            pass  # establishes a -> b
    with b:
        with pytest.raises(LockOrderError) as ei:
            a.acquire()
    err = ei.value
    assert err.lock_a == "t16.order_b"
    assert err.lock_b == "t16.order_a"
    assert err.stack_a and err.stack_b  # both sites captured
    assert violation_count() == 1


def test_witness_transitive_violation_detected():
    a = named_lock("t16.tr_a")
    b = named_lock("t16.tr_b")
    c = named_lock("t16.tr_c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    # a -> b -> c witnessed; c before a contradicts transitively
    with c:
        with pytest.raises(LockOrderError):
            a.acquire()


def test_witness_same_thread_reentry_raises():
    mu = named_lock("t16.reentry")
    with mu:
        with pytest.raises(LockOrderError) as ei:
            mu.acquire()
    assert ei.value.lock_a == ei.value.lock_b == "t16.reentry"
    # the failed re-entry must not have corrupted the held stack
    assert not mu.locked()


def test_witness_rlock_reentry_allowed():
    mu = named_rlock("t16.rlock")
    with mu:
        with mu:
            assert mu._is_owned()
    assert not mu._is_owned()


def test_witness_condition_wait_releases_recursion():
    cv = named_condition("t16.cv")
    hits = []

    def waiter():
        with cv:
            hits.append("waiting")
            cv.wait(timeout=5.0)
            hits.append("woke")

    t = threadreg.spawn("t16-cv-waiter", waiter, daemon=False)
    for _ in range(500):
        if hits:
            break
        time.sleep(0.01)
    with cv:
        cv.notify_all()
    t.join(timeout=5.0)
    assert hits == ["waiting", "woke"]


def test_witness_seed_order_preloads_static_edges():
    added = seed_order([("t16.seed_a", "t16.seed_b")])
    assert added == 1
    a = named_lock("t16.seed_a")
    b = named_lock("t16.seed_b")
    with b:
        with pytest.raises(LockOrderError):
            a.acquire()


def test_witness_distinct_instances_same_name_no_false_positive():
    # per-replica locks share a name; no instance-level order exists
    r0 = named_lock("t16.replica._lock")
    r1 = named_lock("t16.replica._lock")
    with r0:
        with r1:
            pass


# -- thread registry ---------------------------------------------------

def test_threadreg_spawn_tracks_name_and_owner():
    done = threading.Event()
    t = threadreg.spawn(
        "t16-worker", done.wait, args=(5.0,), daemon=False, owner="t16"
    )
    live = threadreg.THREADS.live()
    assert ("t16-worker", "t16", False) in live
    done.set()
    t.join(timeout=5.0)
    assert not any(n == "t16-worker" for n, _o, _d in threadreg.THREADS.live())


def test_threadreg_non_daemon_leak_reported_then_cleared():
    stop = threading.Event()
    t = threadreg.spawn(
        "t16-leak", stop.wait, args=(10.0,), daemon=False, owner="t16"
    )
    leaks = threadreg.THREADS.non_daemon_leaks()
    assert any(s.startswith("t16-leak ") for s in leaks)
    stop.set()
    t.join(timeout=5.0)
    assert not any(
        s.startswith("t16-leak ")
        for s in threadreg.THREADS.non_daemon_leaks()
    )


def test_threadreg_join_all_by_owner():
    evs = [threading.Event() for _ in range(3)]
    for i, ev in enumerate(evs):
        threadreg.spawn(
            f"t16-ja-{i}", ev.wait, args=(10.0,), daemon=False, owner="t16ja"
        )
    for ev in evs:
        ev.set()
    assert not threadreg.THREADS.join_all(timeout=5.0, owner="t16ja")


# -- regression tests for the analyzer-surfaced races ------------------

def test_last_run_info_publish_is_atomic():
    """Seed race: run() did LAST_RUN_INFO.clear() then .update() —
    a concurrent reader could observe the empty dict. The accessor
    pair must never expose a half-published snapshot."""
    from trino_tpu.parallel import mesh_chunk

    payload = {"chunks": 4, "resumes": 0, "chunked": True}
    mesh_chunk.publish_run_info(dict(payload))
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            snap = mesh_chunk.last_run_info()
            if snap and set(snap) != set(payload):
                bad.append(snap)

    threads = [
        threadreg.spawn(f"t16-lri-{i}", reader, daemon=False, owner="t16lri")
        for i in range(2)
    ]
    for _ in range(300):
        mesh_chunk.publish_run_info(dict(payload))
    stop.set()
    assert not threadreg.THREADS.join_all(timeout=5.0, owner="t16lri")
    assert not bad, f"reader saw a torn snapshot: {bad[:3]}"
    del threads


def test_mesh_counters_concurrent_bumps_all_land():
    """Seed race: MESH_COUNTERS[...] += 1 from concurrent query
    threads could drop increments (read-modify-write)."""
    from trino_tpu.parallel.mesh_plan import bump_mesh_counter, mesh_counter

    before = mesh_counter("queries")
    N, PER = 4, 500

    def bump():
        for _ in range(PER):
            bump_mesh_counter("queries")

    ts = [
        threadreg.spawn(f"t16-mc-{i}", bump, daemon=False, owner="t16mc")
        for i in range(N)
    ]
    assert not threadreg.THREADS.join_all(timeout=10.0, owner="t16mc")
    assert mesh_counter("queries") == before + N * PER
    del ts


def test_global_fn_cache_returns_one_identity():
    """Seed race: the unlocked check-then-insert in _global_update_fn
    could mint two jitted callables for one agg spec; every caller must
    get the same object (dispatch caches key on identity)."""
    from trino_tpu.exec.operators import (
        _GLOBAL_FN_CACHE,
        AggSpec,
        _global_update_fn,
    )
    from trino_tpu import types as T

    spec = (AggSpec("count", None, T.BIGINT),)
    _GLOBAL_FN_CACHE.pop((spec, ()), None)
    got = []

    def fetch():
        got.append(_global_update_fn(spec))

    ts = [
        threadreg.spawn(f"t16-fc-{i}", fetch, daemon=False, owner="t16fc")
        for i in range(4)
    ]
    assert not threadreg.THREADS.join_all(timeout=30.0, owner="t16fc")
    assert len(got) == 4
    assert all(g is got[0] for g in got)
    del ts
