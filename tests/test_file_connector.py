"""File connector (connectors/file.py): external-data SPI proof —
schema/type inference, CSV + JSONL, NULLs, splits, writes, DDL, joins
against other catalogs."""

import os

import pytest

from trino_tpu.connectors.file import create_file_connector
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture()
def root(tmp_path):
    sales = tmp_path / "shop" / "sales.csv"
    sales.parent.mkdir(parents=True)
    sales.write_text(
        "day,region,amount,units,returning\n"
        "2024-01-01,east,10.5,3,true\n"
        "2024-01-02,west,20.25,5,false\n"
        "2024-01-02,east,,2,true\n"          # NULL amount
        "2024-01-03,north,7.75,1,\n"         # NULL returning
    )
    people = tmp_path / "shop" / "people.jsonl"
    people.write_text(
        '{"name": "ann", "age": 34, "region": "east"}\n'
        '{"name": "bob", "age": 41, "region": "west"}\n'
        '{"name": "cid", "region": "east"}\n'  # missing age -> NULL
    )
    return str(tmp_path)


@pytest.fixture()
def runner(root):
    r = LocalQueryRunner(Session(catalog="files", schema="shop"))
    r.register_catalog("files", create_file_connector(root))
    return r


def test_schema_discovery(runner):
    assert runner.execute("SHOW TABLES").rows == [["people"], ["sales"]]
    cols = dict(runner.execute("SHOW COLUMNS FROM sales").rows)
    assert cols == {
        "day": "date", "region": "varchar", "amount": "double",
        "units": "bigint", "returning": "boolean",
    }


def test_csv_scan_with_nulls(runner):
    rows = runner.execute(
        "select region, sum(amount), count(amount), count(*)"
        " from sales group by region order by region"
    ).rows
    assert rows == [
        ["east", 10.5, 1, 2], ["north", 7.75, 1, 1], ["west", 20.25, 1, 1],
    ]


def test_date_typing(runner):
    rows = runner.execute(
        "select count(*) from sales where day >= date '2024-01-02'"
    ).rows
    assert rows == [[3]]


def test_boolean_and_filters(runner):
    rows = runner.execute(
        "select units from sales where returning order by units"
    ).rows
    assert rows == [[2], [3]]


def test_jsonl_scan(runner):
    rows = runner.execute(
        "select name, age from people order by name"
    ).rows
    assert rows == [["ann", 34], ["bob", 41], ["cid", None]]


def test_cross_catalog_join(runner, root):
    runner.register_catalog("tpch", create_tpch_connector())
    rows = runner.execute(
        "select p.name, count(*) from people p, tpch.tiny.region r"
        " group by p.name order by p.name"
    ).rows
    assert rows == [["ann", 5], ["bob", 5], ["cid", 5]]


def test_ctas_insert_and_read_back(runner):
    runner.execute(
        "create table files.shop.east_sales as"
        " select day, amount, units from sales where region = 'east'"
    )
    rows = runner.execute(
        "select sum(units) from east_sales"
    ).rows
    assert rows == [[5]]
    # INSERT appends a new part file
    runner.execute(
        "insert into east_sales select day, amount, units from sales"
        " where region = 'west'"
    )
    assert runner.execute("select sum(units) from east_sales").rows == [[10]]


def test_parts_directory_layout(runner, root):
    runner.execute(
        "create table files.shop.t2 as select region from sales"
    )
    d = os.path.join(root, "shop", "t2")
    parts = sorted(p for p in os.listdir(d) if not p.startswith("."))
    assert parts and all(p.startswith("part-") for p in parts)
    assert os.path.isfile(os.path.join(d, ".schema.json"))
    # no temp files left behind
    assert not [p for p in parts if p.endswith(".tmp")]


def test_drop_table(runner):
    runner.execute("create table files.shop.doomed as select 1 as x")
    assert "doomed" in [r[0] for r in runner.execute("SHOW TABLES").rows]
    runner.execute("drop table files.shop.doomed")
    assert "doomed" not in [r[0] for r in runner.execute("SHOW TABLES").rows]


def test_mtime_cache_invalidation(runner, root):
    assert runner.execute("select count(*) from sales").rows == [[4]]
    p = os.path.join(root, "shop", "sales.csv")
    with open(p, "a", newline="") as f:
        f.write("2024-01-04,south,1.0,9,false\n")
    os.utime(p, (os.path.getmtime(p) + 5, os.path.getmtime(p) + 5))
    # plan cache snapshots splits: a fresh runner sees the new row
    r2 = LocalQueryRunner(Session(catalog="files", schema="shop"))
    r2.register_catalog("files", create_file_connector(root))
    assert r2.execute("select count(*) from sales").rows == [[5]]


def test_distributed_scan_over_files(root):
    from trino_tpu.runtime.coordinator import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="files", schema="shop", mesh_execution=False),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("files", create_file_connector(root))
    rows = r.execute(
        "select region, count(*) from sales group by region order by region"
    ).rows
    assert rows == [["east", 2], ["north", 1], ["west", 1]]
