"""Multi-device exchange tests on the virtual 8-device CPU mesh —
the tier-3 DistributedQueryRunner strategy (SURVEY.md §4.3): real
collectives, one process."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PSpec

from trino_tpu.parallel.exchange import distributed_groupby_step


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices()[:8])
    return Mesh(devices, ("shard",))


def _run_step(mesh, rows, n_groups, capacity, with_nulls=False):
    n = mesh.shape["shard"]
    rng = np.random.default_rng(11)
    keys_np = rng.integers(0, n_groups, rows).astype(np.int64)
    vals_np = rng.integers(-50, 1000, rows).astype(np.int64)
    valid_np = (
        rng.random(rows) > 0.1 if with_nulls else np.ones(rows, dtype=bool)
    )
    live_np = rng.random(rows) > 0.05

    sharding = NamedSharding(mesh, PSpec("shard"))
    keys = [jax.device_put(jnp.asarray(keys_np), sharding)]
    valids = [jax.device_put(jnp.asarray(valid_np), sharding)]
    live = jax.device_put(jnp.asarray(live_np), sharding)
    values = [jax.device_put(jnp.asarray(vals_np), sharding)]

    step = distributed_groupby_step(mesh, "shard", capacity, 1)
    ks, vs, used, sums, counts, overflowed = step(keys, valids, live, values)
    assert int(np.asarray(overflowed).max()) == 0

    got = {}
    k_np = np.asarray(ks[0])
    kv_np = np.asarray(vs[0])
    u_np = np.asarray(used)
    s_np = np.asarray(sums[0])
    c_np = np.asarray(counts)
    for k, kv, u, s, c in zip(k_np, kv_np, u_np, s_np, c_np):
        if u:
            # data lane is meaningless for the NULL-key group: normalize
            got[(int(k) if kv else 0, bool(kv))] = (int(s), int(c))

    want = {}
    for k, v, ok, lv in zip(keys_np, vals_np, valid_np, live_np):
        if not lv:
            continue
        kk = (int(k), True) if ok else (0, False)
        s, c = want.get(kk, (0, 0))
        want[kk] = (s + int(v), c + 1)
    return got, want


def test_distributed_groupby_matches_oracle(mesh):
    got, want = _run_step(mesh, rows=8 * 512, n_groups=100, capacity=256)
    assert got == want


def test_distributed_groupby_null_keys(mesh):
    """NULL is one group cluster-wide (validity is part of the key and
    the exchange hash), never one group per shard."""
    got, want = _run_step(
        mesh, rows=8 * 256, n_groups=40, capacity=128, with_nulls=True
    )
    # normalize NULL-key entries: data lane is untracked for invalid keys
    got_null = [v for (k, ok), v in got.items() if not ok]
    want_null = [v for (k, ok), v in want.items() if not ok]
    assert len(got_null) == len(want_null) == 1
    assert got_null[0] == want_null[0]
    assert {k: v for k, v in got.items() if k[1]} == {
        k: v for k, v in want.items() if k[1]
    }


def test_groups_land_on_unique_shards(mesh):
    """Each group exists on exactly one shard after the exchange (the
    FIXED_HASH guarantee that lets final aggregation be local)."""
    got, want = _run_step(mesh, rows=8 * 512, n_groups=64, capacity=256)
    # _run_step already merges per-slot entries into a dict keyed by
    # group; duplicate groups across shards would collide and lose
    # counts, so the totals check below is the uniqueness proof
    assert sum(c for _, c in got.values()) == sum(c for _, c in want.values())
