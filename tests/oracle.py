"""SQLite result oracle — the H2QueryRunner analogue
(testing/trino-testing/…/H2QueryRunner.java, SURVEY.md §4.3): loads the
same generated data into sqlite and cross-checks query results.

Decimals load as exact scaled INTEGERs would lose SQL semantics in
sqlite arithmetic, so they load as REAL; numeric comparisons use
tolerance. Dates load as epoch-day INTEGERs; queries against the oracle
must phrase date literals as epoch days (helpers below).
"""

from __future__ import annotations

import datetime
import sqlite3
from typing import Dict, List, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.tpch import TABLES, base_row_count, generate_column


def epoch_days(s: str) -> int:
    y, m, d = map(int, s.split("-"))
    return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days


# template cache: ~10 tier-1 modules each load the SAME sf=0.01 TPC-H
# dataset at module scope; generating it costs ~1.3s per module where a
# sqlite backup-copy from a shared template costs ~4ms. Templates are
# never handed out — every caller still gets its own connection to
# mutate (the DML tests depend on that isolation).
_TPCH_TEMPLATES: Dict[tuple, sqlite3.Connection] = {}


def load_tpch_sqlite(conn: sqlite3.Connection, sf: float, tables: Sequence[str] = None):
    """Load generated TPC-H data into sqlite tables (same generator, so
    the oracle sees byte-identical data). Loads are served from an
    in-process template cache keyed by (sf, tables)."""
    key = (sf, tuple(tables) if tables else None)
    tmpl = _TPCH_TEMPLATES.get(key)
    if tmpl is None:
        tmpl = sqlite3.connect(":memory:", check_same_thread=False)
        _generate_tpch_sqlite(tmpl, sf, tables)
        _TPCH_TEMPLATES[key] = tmpl
    tmpl.backup(conn)
    conn.commit()


def _generate_tpch_sqlite(conn: sqlite3.Connection, sf: float, tables: Sequence[str] = None):
    for table in tables or TABLES:
        cols = TABLES[table]
        coldefs = ", ".join(
            f"{n} {'TEXT' if t.is_string else 'REAL' if t.is_decimal or t.is_floating else 'INTEGER'}"
            for n, t in cols
        )
        conn.execute(f"CREATE TABLE {table} ({coldefs})")
        n_base = base_row_count(table, sf)
        step = 100_000
        for a in range(0, n_base, step):
            b = min(a + step, n_base)
            arrays = []
            nrows = None
            for name, typ in cols:
                data, d = generate_column(table, name, sf, a, b)
                nrows = len(data)
                if typ.is_string:
                    vals = [d.values[c] for c in data]
                elif typ.is_decimal:
                    sfac = T.decimal_scale_factor(typ)
                    vals = (np.asarray(data, dtype=np.float64) / sfac).tolist()
                else:
                    vals = np.asarray(data).tolist()
                arrays.append(vals)
            rows = list(zip(*arrays))
            ph = ", ".join("?" * len(cols))
            conn.executemany(f"INSERT INTO {table} VALUES ({ph})", rows)
    conn.commit()


def load_tpcds_sqlite(conn: sqlite3.Connection, sf: float, tables: Sequence[str] = None):
    """Load the TPC-DS generator's data into sqlite (same generator,
    byte-identical rows)."""
    from trino_tpu.connectors import tpcds as D

    for table in tables or D.TABLES:
        cols = D.TABLES[table]
        coldefs = ", ".join(
            f"{n} {'TEXT' if t.is_string else 'REAL' if t.is_decimal or t.is_floating else 'INTEGER'}"
            for n, t in cols
        )
        conn.execute(f"CREATE TABLE {table} ({coldefs})")
        n_rows = D.row_count(table, sf)
        step = 100_000
        for a in range(0, n_rows, step):
            b = min(a + step, n_rows)
            arrays = []
            for name, typ in cols:
                data, d = D.generate_column(table, name, sf, a, b)
                if typ.is_string:
                    vals = [d.values[c] for c in data]
                elif typ.is_decimal:
                    sfac = T.decimal_scale_factor(typ)
                    vals = (np.asarray(data, dtype=np.float64) / sfac).tolist()
                else:
                    vals = np.asarray(data).tolist()
                arrays.append(vals)
            ph = ", ".join("?" * len(cols))
            conn.executemany(
                f"INSERT INTO {table} VALUES ({ph})", list(zip(*arrays))
            )
    conn.commit()


def sqlite_rows(conn: sqlite3.Connection, sql: str) -> List[tuple]:
    return [tuple(r) for r in conn.execute(sql).fetchall()]


# memoized oracle answers, keyed by (sf, tables, sql): the TPC-H/window
# cross-check suites ask the SAME oracle queries against the SAME
# immutable template data in several modules. Only for read-only use —
# anything that mutates its database must query its own connection.
_ORACLE_ROWS: Dict[tuple, List[tuple]] = {}


def oracle_rows(sf: float, sql: str, tables: Sequence[str] = None) -> List[tuple]:
    key = (sf, tuple(tables) if tables else None, sql)
    hit = _ORACLE_ROWS.get(key)
    if hit is None:
        tkey = (sf, tuple(tables) if tables else None)
        tmpl = _TPCH_TEMPLATES.get(tkey)
        if tmpl is None:
            tmpl = sqlite3.connect(":memory:", check_same_thread=False)
            _generate_tpch_sqlite(tmpl, sf, tables)
            _TPCH_TEMPLATES[tkey] = tmpl
        hit = _ORACLE_ROWS[key] = sqlite_rows(tmpl, sql)
    return hit


def assert_rows_match(actual: List[list], expected: List[tuple], ordered: bool,
                      rel_tol: float = 1e-9, abs_tol: float = 1e-6):
    def norm(rows):
        return [tuple(r) for r in rows]

    a, e = norm(actual), norm(expected)
    if not ordered:
        a = sorted(a, key=repr)
        e = sorted(e, key=repr)
    assert len(a) == len(e), f"row count {len(a)} != {len(e)}\nactual={a[:5]}\nexpected={e[:5]}"
    for ra, re_ in zip(a, e):
        assert len(ra) == len(re_), f"width {ra} vs {re_}"
        for x, y in zip(ra, re_):
            if isinstance(x, float) or isinstance(y, float):
                if x is None or y is None:
                    assert x is None and y is None, f"{ra} vs {re_}"
                else:
                    assert abs(x - y) <= max(abs_tol, rel_tol * max(abs(x), abs(y))), (
                        f"{x} != {y} in {ra} vs {re_}"
                    )
            else:
                assert x == y, f"{x!r} != {y!r} in row {ra} vs {re_}"
