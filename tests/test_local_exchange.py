"""LocalExchange: intra-task pipeline parallelism
(exec/local_exchange.py — LocalExchange.java:67 analogue)."""

import threading
import time

import pytest

from trino_tpu.exec.local_exchange import (
    LocalExchange,
    LocalExchangeSinkOperator,
    LocalExchangeSourceOperator,
)


def test_single_producer_consumer():
    ex = LocalExchange(n_consumers=1)
    sink = LocalExchangeSinkOperator(ex)
    src = LocalExchangeSourceOperator(ex, 0)
    sink.add_input("b1")
    sink.add_input("b2")
    sink.finish()
    got = []
    while not src.is_finished():
        b = src.get_output()
        if b is not None:
            got.append(b)
    assert got == ["b1", "b2"]


def test_broadcast_mode():
    ex = LocalExchange(n_consumers=2, mode="broadcast")
    sink = LocalExchangeSinkOperator(ex)
    sink.add_input("x")
    sink.finish()
    for c in range(2):
        src = LocalExchangeSourceOperator(ex, c)
        assert src.get_output() == "x"


def test_round_robin_mode():
    ex = LocalExchange(n_consumers=2, mode="round_robin")
    sink = LocalExchangeSinkOperator(ex)
    for i in range(4):
        sink.add_input(i)
    sink.finish()
    a = LocalExchangeSourceOperator(ex, 0)
    b = LocalExchangeSourceOperator(ex, 1)
    got_a = [a.get_output() for _ in range(2)]
    got_b = [b.get_output() for _ in range(2)]
    assert got_a == [0, 2] and got_b == [1, 3]


def test_arbitrary_balances_to_least_loaded():
    ex = LocalExchange(n_consumers=2, mode="arbitrary", max_buffered_batches=8)
    sink = LocalExchangeSinkOperator(ex)
    for i in range(6):
        sink.add_input(i)
    sink.finish()
    assert len(ex._queues[0]) == 3 and len(ex._queues[1]) == 3


def test_multi_producer_completion():
    ex = LocalExchange(n_consumers=1)
    s1 = LocalExchangeSinkOperator(ex)
    s2 = LocalExchangeSinkOperator(ex)
    s1.add_input("a")
    s1.finish()
    src = LocalExchangeSourceOperator(ex, 0)
    assert src.get_output() == "a"
    # one producer still open: not finished
    assert not src.is_finished()
    s2.add_input("b")
    s2.finish()
    got = []
    while not src.is_finished():
        b = src.get_output()
        if b is not None:
            got.append(b)
    assert got == ["b"]


def test_producer_failure_raises_in_consumer():
    """A dead producer must FAIL the consumer, not read as clean
    end-of-input: before producer_failed existed, the task-concurrency
    split turned a killed upstream into an empty 'complete' result
    (the deadline-kill-returns-empty-success race in
    TaskExecution._run_pipelines)."""
    ex = LocalExchange(n_consumers=1)
    sink = LocalExchangeSinkOperator(ex)
    sink.add_input("a")
    boom = RuntimeError("exchange pull failed")
    ex.producer_failed(boom)
    src = LocalExchangeSourceOperator(ex, 0)
    with pytest.raises(RuntimeError, match="producer failed") as ei:
        src.get_output()
    assert ei.value.__cause__ is boom
    # the latch is sticky: a consumer polling is_blocked() fails too
    with pytest.raises(RuntimeError, match="producer failed"):
        src.is_blocked()


def test_backpressure_bounds_buffering():
    ex = LocalExchange(n_consumers=1, max_buffered_batches=2)
    sink = LocalExchangeSinkOperator(ex)
    sink.add_input(1)
    sink.add_input(2)
    blocked = threading.Event()
    passed = threading.Event()

    def push():
        blocked.set()
        sink.add_input(3)  # must wait until a slot frees
        passed.set()

    t = threading.Thread(target=push, daemon=True)
    t.start()
    blocked.wait()
    time.sleep(0.05)
    assert not passed.is_set()  # producer is throttled
    src = LocalExchangeSourceOperator(ex, 0)
    assert src.get_output() == 1
    t.join(5)
    assert passed.is_set()


def test_threaded_pipeline_overlap():
    """Producer thread + consumer thread through the exchange."""
    ex = LocalExchange(n_consumers=1, max_buffered_batches=2)
    sink = LocalExchangeSinkOperator(ex)
    src = LocalExchangeSourceOperator(ex, 0)
    N = 50

    def produce():
        for i in range(N):
            sink.add_input(i)
        sink.finish()

    got = []

    def consume():
        while not src.is_finished():
            b = src.get_output()
            if b is not None:
                got.append(b)

    tp = threading.Thread(target=produce, daemon=True)
    tc = threading.Thread(target=consume, daemon=True)
    tp.start(); tc.start()
    tp.join(10); tc.join(10)
    assert got == list(range(N))


# -- end to end: distributed queries with intra-task parallelism on --


def test_distributed_with_task_concurrency(tpch_cluster_mesh_off):
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime.coordinator import DistributedQueryRunner

    # the shared page-plane cluster runs at the session default
    # task_concurrency=2 — exactly the concurrent arm this test needs
    r = tpch_cluster_mesh_off
    assert r.session.task_concurrency == 2
    # multi-build join + distributed agg: builds run concurrently and
    # the final stage overlaps remote pulls with compute
    rows = r.execute(
        "select n_name, count(*) c from customer, nation"
        " where c_nationkey = n_nationkey group by n_name"
        " order by c desc, n_name limit 5"
    ).rows
    assert len(rows) == 5 and all(len(row) == 2 for row in rows)
    off = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", mesh_execution=False,
                task_concurrency=1),
        n_workers=2, hash_partitions=2,
    )
    off.register_catalog("tpch", create_tpch_connector())
    assert off.execute(
        "select n_name, count(*) c from customer, nation"
        " where c_nationkey = n_nationkey group by n_name"
        " order by c desc, n_name limit 5"
    ).rows == rows


# -- skewed-partition rebalancer (exchange_ops.SkewedPartitionRebalancer) --


def test_rebalancer_balances_uneven_pages():
    from trino_tpu.exec.exchange_ops import SkewedPartitionRebalancer

    rb = SkewedPartitionRebalancer(3)
    # one huge page then many small: small pages route AWAY from the
    # partition holding the huge one
    first = rb.pick(1000)
    for _ in range(10):
        assert rb.pick(10) != first
    assert rb.skew() < 3.0


def test_rebalancer_even_stream_round_robins():
    from trino_tpu.exec.exchange_ops import SkewedPartitionRebalancer

    rb = SkewedPartitionRebalancer(4)
    picks = [rb.pick(100) for _ in range(8)]
    assert sorted(picks) == [0, 0, 1, 1, 2, 2, 3, 3]
    assert abs(rb.skew() - 1.0) < 1e-9
