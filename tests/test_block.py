"""Columnar core tests — analogue of trino-spi block/type unit tests."""

import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity


def test_bucket_capacity():
    assert bucket_capacity(0) == 16
    assert bucket_capacity(16) == 16
    assert bucket_capacity(17) == 32
    assert bucket_capacity(1000) == 1024


def test_column_roundtrip_ints():
    c = Column.from_pylist(T.BIGINT, [1, 2, None, 4])
    assert c.capacity == 16
    assert c.to_pylist(count=4) == [1, 2, None, 4]


def test_column_roundtrip_strings():
    c = Column.from_pylist(T.VARCHAR, ["b", "a", None, "b"])
    assert c.to_pylist(count=4) == ["b", "a", None, "b"]
    # sorted dictionary → code order == lexical order
    assert c.dictionary.values == ("a", "b")


def test_column_decimal():
    t = T.decimal(12, 2)
    c = Column.from_numpy(t, np.asarray([12345, -50], dtype=np.int64))
    assert c.to_pylist(count=2) == [123.45, -0.5]


def test_dictionary_unify():
    a, b = Dictionary(["x", "y"]), Dictionary(["y", "z"])
    m, ra, rb = Dictionary.unify(a, b)
    assert m.values == ("x", "y", "z")
    assert list(ra) == [0, 1] and list(rb) == [1, 2]


def test_batch_mask_compact_roundtrip():
    b = RelBatch.from_pydict(
        [("k", T.BIGINT), ("v", T.DOUBLE)],
        {"k": [1, 2, 3, 4, 5], "v": [1.0, 2.0, 3.0, 4.0, 5.0]},
    )
    assert b.row_count() == 5
    import jax.numpy as jnp

    keep = jnp.asarray([True, False, True, False, True] + [True] * 11)
    f = b.mask(keep)
    assert f.row_count() == 3
    c = f.compact()
    assert c.row_count() == 3
    assert c.to_pylists() == [[1, 1.0], [3, 3.0], [5, 5.0]]


def test_batch_gather():
    import jax.numpy as jnp

    b = RelBatch.from_pydict([("k", T.INTEGER)], {"k": [10, 20, 30]})
    g = b.gather(jnp.asarray([2, 0]), jnp.asarray([True, True]))
    assert g.to_pylists() == [[30], [10]]
