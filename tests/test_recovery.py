"""Recovery-tier tests (PR 14): chunk checkpoints + spooled stage reuse.

The mesh plane checkpoints its chunk loop at
`mesh_checkpoint_interval_chunks` boundaries (recovery/checkpoint.py),
so a MeshStuck / MeshDeviceLost mid-run resumes from the last snapshot
instead of chunk 0; the page plane tees completed fragment outputs into
the subtree spool (recovery/stage_spool.py), so QUERY retry replays
settled stages instead of recomputing them. These tests pin:

  - byte-identity of a resumed run against an uninterrupted one, with
    the fault at chunk 0 (no checkpoint yet -> observable page-plane
    fallback), mid-run and at the last chunk;
  - checkpoint invalidation: INSERT / UPDATE on a feed table drops its
    checkpoints (eager DML path AND the lazy generation guard);
  - spooled-stage reuse on QUERY retry substitutes completed fragments
    with ZERO upstream re-execution;
  - a resumed run mints zero new XLA lowerings (the warm capacity
    ladder + program-cache records survive the fault);
  - a deadline kill landing during the resumed stretch keeps the typed
    [EXCEEDED_TIME_LIMIT] error (resuming never refreshes a budget).
"""

import time

import pytest

from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.parallel import mesh_chunk, mesh_plan
from trino_tpu.recovery import CHECKPOINTS, MeshCheckpoint
from trino_tpu.resident import GENERATIONS
from trino_tpu.runtime import DistributedQueryRunner
from trino_tpu.runtime.failure import FailureInjector
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_TIME_LIMIT,
    DeadlineLimits,
    ExceededTimeLimitError,
    QueryDeadlineError,
    QueryTracker,
    preemption_check,
)
from trino_tpu.runtime.worker import Worker

# exact-valued aggregates only (int results): chunked accumulation and
# resume must both be byte-identical to the page plane
Q_GROUP = (
    "select l_returnflag, l_linestatus, count(*) c, "
    "sum(l_quantity) q, min(l_orderkey) mn, max(l_orderkey) mx "
    "from lineitem group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)


def mk_runner(**session_kw):
    kw = dict(
        mesh_chunk_rows=512, mesh_checkpoint_interval_chunks=1,
    )
    kw.update(session_kw)
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", **kw),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(autouse=True)
def _clean_recovery_state():
    """Every test starts with an empty checkpoint store and no fault
    hook (a leaked one-shot hook would fire in an unrelated test)."""
    CHECKPOINTS.clear()
    mesh_chunk.MESH_FAULT_HOOK = None
    yield
    CHECKPOINTS.clear()
    mesh_chunk.MESH_FAULT_HOOK = None


@pytest.fixture(scope="module")
def baseline_rows(tpch_cluster_mesh_off):
    # read-only query on the shared page-plane cluster (tier-1 wall)
    return tpch_cluster_mesh_off.execute(Q_GROUP).rows


class OneShotFault:
    """MESH_FAULT_HOOK that raises `exc` the first time the chunk loop
    reaches `target`; subsequent arrivals (the resumed stretch) pass."""

    def __init__(self, target, exc=mesh_chunk.MeshStuck):
        self.target = target
        self.exc = exc
        self.fired = False

    def __call__(self, k, K):
        if not self.fired and k == self.target:
            self.fired = True
            raise self.exc(f"injected mesh fault at chunk {k}/{K}")


# -- byte-identity across fault points ---------------------------------


@pytest.mark.parametrize("where", ["mid", "last"])
def test_resume_byte_identical(where, baseline_rows):
    """A fault mid-run or at the last chunk resumes from the latest
    checkpoint: identical rows, stays on the mesh, and (interval=1)
    re-executes ZERO chunks."""
    r = mk_runner()
    assert r.execute(Q_GROUP).rows == baseline_rows  # warm, no fault
    K = mesh_chunk.LAST_RUN_INFO["chunks"]
    assert K >= 4, f"query too small to chunk ({K})"
    target = K // 2 if where == "mid" else K - 1
    fault = OneShotFault(target, mesh_chunk.MeshDeviceLost)
    mesh_chunk.MESH_FAULT_HOOK = fault
    before = mesh_plan.MESH_COUNTERS["queries"]
    assert r.execute(Q_GROUP).rows == baseline_rows
    assert fault.fired
    info = mesh_chunk.LAST_RUN_INFO
    assert mesh_plan.MESH_COUNTERS["queries"] == before + 1, \
        f"fell back to HTTP: {r.last_mesh_fallback}"
    assert info["resumes"] == 1
    assert info["resumed_from_chunk"] == target
    assert info["executed_chunk_steps"] == K, \
        "resume re-executed already-completed chunks"


def test_fault_at_chunk_zero_falls_back(baseline_rows):
    """Chunk 0 precedes the first checkpoint, so there is nothing to
    resume from: the fault keeps its retryable type and the coordinator
    takes the OBSERVABLE page-plane fallback — correct rows, reason
    recorded, no resume counted."""
    r = mk_runner()
    assert r.execute(Q_GROUP).rows == baseline_rows  # warm
    resumed0 = CHECKPOINTS.resumed
    fault = OneShotFault(0, mesh_chunk.MeshStuck)
    mesh_chunk.MESH_FAULT_HOOK = fault
    fallbacks = mesh_plan.MESH_COUNTERS["fallbacks"]
    assert r.execute(Q_GROUP).rows == baseline_rows
    assert fault.fired
    assert mesh_plan.MESH_COUNTERS["fallbacks"] == fallbacks + 1
    assert r.last_mesh_fallback is not None
    assert CHECKPOINTS.resumed == resumed0


# -- checkpoint invalidation on DML ------------------------------------


def _fake_ckpt(tables):
    return MeshCheckpoint(
        next_chunk=1, n_chunks=4, chunk_cap=512, resolved_caps={},
        carries_host=(), tables=tables,
        generations=GENERATIONS.snapshot(tables),
    )


def test_insert_and_update_invalidate_checkpoints():
    """The engine's DML path drops checkpoints keyed to the written
    table (eagerly, via invalidate_table) while leaving checkpoints on
    other tables alone."""
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", create_memory_connector())
    r.execute("CREATE TABLE ckpt_t (a bigint, b varchar)")
    r.execute("INSERT INTO ckpt_t VALUES (1, 'x'), (2, 'y')")

    fed = (("memory", "default", "ckpt_t"),)
    other = (("tpch", "tiny", "lineitem"),)
    CHECKPOINTS.put(("mesh-ckpt", "fed"), _fake_ckpt(fed))
    CHECKPOINTS.put(("mesh-ckpt", "other"), _fake_ckpt(other))
    inv0 = CHECKPOINTS.invalidated

    r.execute("INSERT INTO ckpt_t VALUES (3, 'z')")
    assert CHECKPOINTS.get(("mesh-ckpt", "fed")) is None, \
        "INSERT must invalidate checkpoints over the written table"
    assert CHECKPOINTS.get(("mesh-ckpt", "other")) is not None, \
        "INSERT must not touch checkpoints over other tables"
    assert CHECKPOINTS.invalidated > inv0

    CHECKPOINTS.put(("mesh-ckpt", "fed"), _fake_ckpt(fed))
    r.execute("UPDATE ckpt_t SET b = 'w' WHERE a = 1")
    assert CHECKPOINTS.get(("mesh-ckpt", "fed")) is None, \
        "UPDATE must invalidate checkpoints over the written table"
    r.execute("DROP TABLE ckpt_t")


def test_generation_guard_catches_unannounced_write():
    """Even without the eager DML hook, `get` revalidates the snapshot
    generation vector: a bumped feed-table generation makes the entry
    unreachable (counted as an invalidation) instead of serving stale
    carries."""
    tables = (("memory", "default", "gen_t"),)
    CHECKPOINTS.put(("mesh-ckpt", "gen"), _fake_ckpt(tables))
    assert CHECKPOINTS.get(("mesh-ckpt", "gen")) is not None
    inv0 = CHECKPOINTS.invalidated
    GENERATIONS.bump(tables[0])
    assert CHECKPOINTS.get(("mesh-ckpt", "gen")) is None
    assert CHECKPOINTS.invalidated == inv0 + 1


# -- spooled stage reuse on QUERY retry --------------------------------


def test_spooled_stage_reuse_zero_upstream_reexecution():
    """A QUERY retry substitutes every fully-recorded completed
    fragment with its spooled output: same rows as a clean run, and the
    retry attempt never re-schedules the substituted fragment's
    producers (zero upstream re-execution)."""
    sql = (
        "select n_name, count(*) c from supplier, nation "
        "where s_nationkey = n_nationkey group by n_name order by n_name"
    )
    inj = FailureInjector()
    cats = CatalogManager()
    workers = [
        Worker(f"rec-w{i}", cats, failure_injector=inj) for i in range(2)
    ]
    runner = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="query",
                query_retry_count=2, recovery_spool_stages=True),
        worker_handles=workers, hash_partitions=2,
    )
    conn = create_tpch_connector()
    runner.register_catalog("tpch", conn)
    cats.register("tpch", conn)

    expected = runner.execute(sql).rows
    root_id = max(si["fragment_id"] for si in runner._last_stage_infos)

    created = []
    orig = Worker.create_task

    def spy(self, spec):
        created.append(str(spec.task_id))
        return orig(self, spec)

    Worker.create_task = spy
    hits0 = METRICS.snapshot().get("recovery.spooled_stage_hits", 0.0)
    inj.inject(where="mid", fragment_id=root_id, attempts=(0,), max_hits=1)
    try:
        rows = runner.execute(sql).rows
    finally:
        Worker.create_task = orig
        inj.clear()

    assert rows == expected
    hits = METRICS.snapshot().get("recovery.spooled_stage_hits", 0.0) - hits0
    assert hits >= 1, "retry did not substitute any spooled stage"
    retry_tasks = [t for t in created if "r1." in t]
    assert retry_tasks, "no retry attempt ran"
    # the substituted fragment's producers (scan stages, fragment 0)
    # must not re-run: the deepest fragment id in the retry namespace
    # is the replay fragment, not a scan
    retry_fids = {int(t.split(".")[1]) for t in retry_tasks}
    assert 0 not in retry_fids, \
        f"retry re-executed upstream scan fragments: {sorted(retry_fids)}"
    assert root_id in retry_fids


# -- warm resume: zero new lowerings -----------------------------------


def test_resume_zero_new_lowerings(baseline_rows):
    """Resuming lands on the SAME program-cache records and ladder
    rungs as the faulted run: no new XLA programs are lowered."""
    r = mk_runner()
    assert r.execute(Q_GROUP).rows == baseline_rows  # warm
    K = mesh_chunk.LAST_RUN_INFO["chunks"]
    mesh_chunk.MESH_FAULT_HOOK = OneShotFault(
        max(K // 2, 1), mesh_chunk.MeshDeviceLost
    )
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    assert r.execute(Q_GROUP).rows == baseline_rows
    delta = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    assert delta == 0, f"resume lowered {delta:g} new XLA programs"
    assert mesh_chunk.LAST_RUN_INFO["resumes"] == 1


# -- deadline kills during resume stay typed ---------------------------


def test_deadline_message_names_resume_point():
    """The chunk-boundary wall check embeds the resume origin in its
    kill message while keeping the typed [EXCEEDED_TIME_LIMIT] code —
    resuming does not refresh a spent budget."""
    tracker = QueryTracker()
    tracker.register("qx", DeadlineLimits())
    check = preemption_check(
        tracker, "qx", deadline_epoch_s=time.time() - 1.0
    )
    check.resumed_from = 7
    with pytest.raises(ExceededTimeLimitError) as ei:
        check(9, 16)
    msg = str(ei.value)
    assert EXCEEDED_TIME_LIMIT in msg
    assert "(resumed from chunk 7)" in msg
    assert "9/16" in msg


def test_deadline_kill_during_resume_stays_typed(baseline_rows):
    """A tracker kill latched while the resumed stretch is executing
    surfaces as the typed, non-retryable deadline error — no page-plane
    fallback, no silent retry."""
    r = mk_runner()
    assert r.execute(Q_GROUP).rows == baseline_rows  # warm
    K = mesh_chunk.LAST_RUN_INFO["chunks"]
    target = K // 2
    state = {"faulted": False}

    def hook(k, K_):
        if not state["faulted"] and k == target:
            state["faulted"] = True
            raise mesh_chunk.MeshDeviceLost("injected fault")
        if state["faulted"]:
            # the resumed stretch: latch a deadline kill exactly as the
            # enforcement tick would
            for tq in list(r.query_tracker._queries.values()):
                if tq.error is None:
                    tq.error = ExceededTimeLimitError(
                        f"Query {tq.query_id} exceeded the execution "
                        f"time limit [{EXCEEDED_TIME_LIMIT}]"
                    )

    mesh_chunk.MESH_FAULT_HOOK = hook
    resumed0 = CHECKPOINTS.resumed
    with pytest.raises(QueryDeadlineError) as ei:
        r.execute(Q_GROUP)
    assert EXCEEDED_TIME_LIMIT in str(ei.value)
    assert CHECKPOINTS.resumed == resumed0 + 1, "fault did not resume"
    assert r.last_mesh_fallback is None, \
        "typed deadline error must not trigger a page-plane fallback"
