"""SQL semantics regression tests for the formerly-deviant behaviors
(VERDICT r1 item #7): NULL-aware NOT IN, scalar-subquery zero-row NULL /
multi-row error, decimal division and avg typing. Each case cross-checks
the engine against sqlite running the same statement."""

import sqlite3

import pytest

from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def env():
    r = LocalQueryRunner(Session(catalog="memory", schema="t"))
    r.register_catalog("memory", create_memory_connector())
    conn = sqlite3.connect(":memory:")
    ddl = [
        "create table a (x bigint)",
        "insert into a values (1), (2), (null)",
        "create table b (y bigint)",
        "insert into b values (2), (3)",
        "create table bn (y bigint)",
        "insert into bn values (2), (null)",
        "create table empty_t (z bigint)",
        "create table one_t (z bigint)",
        "insert into one_t values (2)",
    ]
    for stmt in ddl:
        r.execute(
            stmt.replace("create table ", "create table memory.t.")
            if stmt.startswith("create table")
            else stmt
        )
        conn.execute(stmt.replace(" bigint", " integer"))
    yield r, conn
    conn.close()


def _key(row):
    return tuple((v is None, v if v is not None else 0) for v in row)


def both(env, sql):
    r, conn = env
    got = sorted(map(tuple, r.execute(sql).rows), key=_key)
    want = sorted(map(tuple, conn.execute(sql).fetchall()), key=_key)
    assert got == want, (sql, got, want)
    return got


class TestNullAwareNotIn:
    def test_not_in_without_nulls(self, env):
        assert both(env, "select x from a where x not in (select y from b)") \
            == [(1,)]

    def test_not_in_with_null_in_subquery_is_empty(self, env):
        assert both(env, "select x from a where x not in (select y from bn)") \
            == []

    def test_not_in_null_probe_dropped(self, env):
        # NULL NOT IN (non-empty set) is UNKNOWN -> row dropped
        rows = both(env, "select x from a where x not in (select y from b)")
        assert (None,) not in rows

    def test_not_in_empty_subquery_keeps_all_rows(self, env):
        # x NOT IN (empty set) is TRUE for every row, NULL x included
        assert both(
            env, "select x from a where x not in (select z from empty_t)"
        ) == [(1,), (2,), (None,)]

    def test_in_still_matches(self, env):
        assert both(env, "select x from a where x in (select y from b)") \
            == [(2,)]


class TestScalarSubqueryCardinality:
    def test_zero_rows_yields_null(self, env):
        # NULL comparison -> no rows, but outer rows must NOT error
        assert both(
            env, "select x from a where x = (select z from empty_t)"
        ) == []

    def test_zero_rows_null_visible_through_coalesce(self, env):
        assert both(
            env,
            "select count(*) from a "
            "where coalesce((select z from empty_t), 1) = 1",
        ) == [(3,)]

    def test_single_row_passes(self, env):
        assert both(
            env, "select x from a where x = (select z from one_t)"
        ) == [(2,)]

    def test_multi_row_raises(self, env):
        r, _ = env
        with pytest.raises(Exception, match="multiple rows"):
            r.execute("select x from a where x = (select y from b)")

    def test_global_aggregate_skips_guard(self, env):
        assert both(
            env, "select x from a where x = (select max(z) from empty_t)"
        ) == []


class TestDecimalTyping:
    @pytest.fixture(scope="class")
    def dec(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="t"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("create table memory.t.d (p decimal(12,2), q decimal(12,2))")
        r.execute("insert into d values (10.00, 4.00), (1.00, 3.00)")
        return r

    def test_division_is_decimal_typed(self, dec):
        # Trino: decimal(12,2)/decimal(12,2) -> decimal(14,2)
        # (DecimalOperators: p = min(38, p1+s2+max(s2-s1,0)), s = max)
        res = dec.execute("select p / q from d order by 1")
        assert str(res.column_types[0]) == "decimal(14,2)"
        assert res.rows == [[0.33], [2.5]]

    def test_avg_decimal_keeps_scale(self, dec):
        res = dec.execute("select avg(p) from d")
        assert str(res.column_types[0]).startswith("decimal")
        assert res.rows == [[5.5]]

    def test_division_by_zero_is_null_free_error_shape(self, dec):
        # engine maps x/0 for decimals to NULL-marked invalid rows
        res = dec.execute("select p / (q - q) from d")
        assert all(v is None for (v,) in res.rows)
