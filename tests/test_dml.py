"""DDL/DML (CREATE/INSERT/CTAS/DROP via TableWriter) + VALUES +
GROUPING SETS/ROLLUP/CUBE (SURVEY.md §2.6 table writes, §2.2)."""

import sqlite3

import pytest

from tests.oracle import load_tpch_sqlite, sqlite_rows
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture()
def runner():
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", create_memory_connector())
    r.register_catalog("tpch", create_tpch_connector())
    return r


def test_create_insert_select_drop(runner):
    assert runner.execute("CREATE TABLE t (a bigint, b varchar, c double)").rows
    assert runner.execute(
        "INSERT INTO t VALUES (1, 'x', 1.5), (2, 'y', -2.25), (3, NULL, 0.0)"
    ).only_value() == 3
    assert runner.execute("SELECT * FROM t ORDER BY a").rows == [
        [1, "x", 1.5], [2, "y", -2.25], [3, None, 0.0],
    ]
    # partial column list: missing columns become NULL
    assert runner.execute("INSERT INTO t (a) VALUES (99)").only_value() == 1
    assert runner.execute("SELECT count(*), sum(a) FROM t").rows == [[4, 105]]
    runner.execute("DROP TABLE t")
    with pytest.raises(Exception):
        runner.execute("SELECT * FROM t")


def test_insert_from_query_with_coercion(runner):
    runner.execute("CREATE TABLE s (k bigint, total double)")
    n = runner.execute(
        "INSERT INTO s SELECT n_regionkey, count(*) FROM tpch.tiny.nation"
        " GROUP BY n_regionkey"
    ).only_value()
    assert n == 5
    assert runner.execute("SELECT sum(total) FROM s").only_value() == 25.0


def test_ctas(runner):
    runner.execute(
        "CREATE TABLE agg AS SELECT n_regionkey, count(*) c"
        " FROM tpch.tiny.nation GROUP BY n_regionkey"
    )
    assert runner.execute("SELECT * FROM agg ORDER BY n_regionkey").rows == [
        [i, 5] for i in range(5)
    ]


def test_values_standalone(runner):
    assert runner.execute("VALUES (1, 'a'), (2, 'b')").rows == [
        [1, "a"], [2, "b"],
    ]
    assert runner.execute("SELECT 1 UNION ALL VALUES (2)").rows in (
        [[1], [2]], [[2], [1]],
    )


# -- grouping sets ----------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(scope="module")
def oracle():
    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, 0.01)
    yield conn
    conn.close()


def _norm(rows):
    key = lambda r: tuple((v is None, v) for v in r)  # noqa: E731
    return sorted(
        [[round(v, 2) if isinstance(v, float) else v for v in r] for r in rows],
        key=key,
    )


GS_CASES = [
    (
        "select n_regionkey, count(*) c from nation group by rollup(n_regionkey)",
        "select n_regionkey, count(*) from nation group by n_regionkey"
        " union all select null, count(*) from nation",
    ),
    (
        "select l_returnflag, l_linestatus, sum(l_quantity) q from lineitem"
        " group by cube(l_returnflag, l_linestatus)",
        "select l_returnflag, l_linestatus, sum(l_quantity) from lineitem group by 1,2"
        " union all select l_returnflag, null, sum(l_quantity) from lineitem group by 1"
        " union all select null, l_linestatus, sum(l_quantity) from lineitem group by 2"
        " union all select null, null, sum(l_quantity) from lineitem",
    ),
    (
        "select l_returnflag, l_linestatus, count(*) from lineitem"
        " group by grouping sets ((l_returnflag), (l_linestatus))",
        "select l_returnflag, null, count(*) from lineitem group by 1"
        " union all select null, l_linestatus, count(*) from lineitem group by 2",
    ),
]


@pytest.mark.parametrize("sql,oracle_sql", GS_CASES)
def test_grouping_sets(sql, oracle_sql, tpch_runner, oracle):
    got = _norm(tpch_runner.execute(sql).rows)
    want = _norm(sqlite_rows(oracle, oracle_sql))
    assert got == want
