"""DDL/DML (CREATE/INSERT/CTAS/DROP via TableWriter) + VALUES +
GROUPING SETS/ROLLUP/CUBE (SURVEY.md §2.6 table writes, §2.2)."""

import sqlite3

import pytest

from tests.oracle import load_tpch_sqlite, sqlite_rows
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture()
def runner():
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", create_memory_connector())
    r.register_catalog("tpch", create_tpch_connector())
    return r


def test_create_insert_select_drop(runner):
    assert runner.execute("CREATE TABLE t (a bigint, b varchar, c double)").rows
    assert runner.execute(
        "INSERT INTO t VALUES (1, 'x', 1.5), (2, 'y', -2.25), (3, NULL, 0.0)"
    ).only_value() == 3
    assert runner.execute("SELECT * FROM t ORDER BY a").rows == [
        [1, "x", 1.5], [2, "y", -2.25], [3, None, 0.0],
    ]
    # partial column list: missing columns become NULL
    assert runner.execute("INSERT INTO t (a) VALUES (99)").only_value() == 1
    assert runner.execute("SELECT count(*), sum(a) FROM t").rows == [[4, 105]]
    runner.execute("DROP TABLE t")
    with pytest.raises(Exception):
        runner.execute("SELECT * FROM t")


def test_insert_from_query_with_coercion(runner):
    runner.execute("CREATE TABLE s (k bigint, total double)")
    n = runner.execute(
        "INSERT INTO s SELECT n_regionkey, count(*) FROM tpch.tiny.nation"
        " GROUP BY n_regionkey"
    ).only_value()
    assert n == 5
    assert runner.execute("SELECT sum(total) FROM s").only_value() == 25.0


def test_ctas(runner):
    runner.execute(
        "CREATE TABLE agg AS SELECT n_regionkey, count(*) c"
        " FROM tpch.tiny.nation GROUP BY n_regionkey"
    )
    assert runner.execute("SELECT * FROM agg ORDER BY n_regionkey").rows == [
        [i, 5] for i in range(5)
    ]


def test_values_standalone(runner):
    assert runner.execute("VALUES (1, 'a'), (2, 'b')").rows == [
        [1, "a"], [2, "b"],
    ]
    assert runner.execute("SELECT 1 UNION ALL VALUES (2)").rows in (
        [[1], [2]], [[2], [1]],
    )


# -- grouping sets ----------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(scope="module")
def oracle():
    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, 0.01)
    yield conn
    conn.close()


def _norm(rows):
    key = lambda r: tuple((v is None, v) for v in r)  # noqa: E731
    return sorted(
        [[round(v, 2) if isinstance(v, float) else v for v in r] for r in rows],
        key=key,
    )


GS_CASES = [
    (
        "select n_regionkey, count(*) c from nation group by rollup(n_regionkey)",
        "select n_regionkey, count(*) from nation group by n_regionkey"
        " union all select null, count(*) from nation",
    ),
    (
        "select l_returnflag, l_linestatus, sum(l_quantity) q from lineitem"
        " group by cube(l_returnflag, l_linestatus)",
        "select l_returnflag, l_linestatus, sum(l_quantity) from lineitem group by 1,2"
        " union all select l_returnflag, null, sum(l_quantity) from lineitem group by 1"
        " union all select null, l_linestatus, sum(l_quantity) from lineitem group by 2"
        " union all select null, null, sum(l_quantity) from lineitem",
    ),
    (
        "select l_returnflag, l_linestatus, count(*) from lineitem"
        " group by grouping sets ((l_returnflag), (l_linestatus))",
        "select l_returnflag, null, count(*) from lineitem group by 1"
        " union all select null, l_linestatus, count(*) from lineitem group by 2",
    ),
]


@pytest.mark.parametrize("sql,oracle_sql", GS_CASES)
def test_grouping_sets(sql, oracle_sql, tpch_runner, oracle):
    got = _norm(tpch_runner.execute(sql).rows)
    want = _norm(sqlite_rows(oracle, oracle_sql))
    assert got == want


class TestDeleteUpdate:
    """DELETE / UPDATE via read-rewrite (the memory-connector analogue
    of Trino's row-level delete/update; SURVEY.md §2.6 TableDelete)."""

    @staticmethod
    def _runner():
        from trino_tpu.connectors.memory import create_memory_connector

        r = LocalQueryRunner(Session(catalog="memory", schema="s"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("CREATE TABLE t (x bigint, name varchar)")
        r.execute(
            "INSERT INTO t VALUES (1, 'a'), (2, 'b'), (3, 'c'), (4, 'd')"
        )
        return r

    def test_delete_predicate(self):
        r = self._runner()
        assert r.execute("DELETE FROM t WHERE x > 2").only_value() == 2
        assert r.execute("SELECT x FROM t ORDER BY x").rows == [[1], [2]]

    def test_delete_all(self):
        r = self._runner()
        assert r.execute("DELETE FROM t").only_value() == 4
        assert r.execute("SELECT count(*) FROM t").only_value() == 0
        # table still exists and accepts inserts
        r.execute("INSERT INTO t VALUES (9, 'z')")
        assert r.execute("SELECT count(*) FROM t").only_value() == 1

    def test_delete_null_predicate_keeps_row(self):
        r = self._runner()
        r.execute("INSERT INTO t VALUES (NULL, 'n')")
        # x > 2 is NULL for the NULL row -> not deleted
        assert r.execute("DELETE FROM t WHERE x > 2").only_value() == 2
        assert r.execute("SELECT count(*) FROM t").only_value() == 3

    def test_update_with_predicate(self):
        r = self._runner()
        assert (
            r.execute("UPDATE t SET name = 'z', x = x + 10 WHERE x = 2").only_value()
            == 1
        )
        rows = r.execute("SELECT x, name FROM t ORDER BY x").rows
        assert rows == [[1, "a"], [3, "c"], [4, "d"], [12, "z"]]

    def test_update_all_rows_with_coercion(self):
        r = self._runner()
        # x + 0.5 is DOUBLE: the rewrite must cast back onto the BIGINT
        # column (round half away: 1.5->2, 2.5->3, 3.5->4, 4.5->5)
        assert r.execute("UPDATE t SET x = x + 0.5").only_value() == 4
        assert r.execute("SELECT sum(x) FROM t").only_value() == 14

    def test_duplicate_assignment_rejected(self):
        from trino_tpu.sql.analyzer import AnalysisError

        r = self._runner()
        with pytest.raises(AnalysisError):
            r.execute("UPDATE t SET x = 1, x = 2")

    def test_update_requires_update_privilege(self):
        from trino_tpu.connectors.memory import create_memory_connector
        from trino_tpu.security import AccessDeniedError, FileBasedAccessControl

        ac = FileBasedAccessControl(
            [{"user": "u", "privileges": ["SELECT", "INSERT", "OWNERSHIP"]}]
        )
        r = LocalQueryRunner(
            Session(catalog="memory", schema="s", user="u"), access_control=ac
        )
        r.register_catalog("memory", create_memory_connector())
        r.execute("CREATE TABLE t (x bigint)")
        r.execute("INSERT INTO t VALUES (1)")
        # drop to INSERT-only: UPDATE must be denied (insert != update)
        r.access_control = FileBasedAccessControl(
            [{"user": "u", "privileges": ["SELECT", "INSERT"]}]
        )
        with pytest.raises(AccessDeniedError):
            r.execute("UPDATE t SET x = 0")

    def test_dml_subquery_scan_is_checked(self):
        """The rewrite query's scans go through access control — a
        WHERE-clause subquery must not read tables the user cannot
        SELECT from."""
        from trino_tpu.connectors.memory import create_memory_connector
        from trino_tpu.security import AccessDeniedError, FileBasedAccessControl

        r = LocalQueryRunner(Session(catalog="memory", schema="s", user="u"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("CREATE TABLE t (x bigint)")
        r.execute("CREATE TABLE secret (v bigint)")
        r.execute("INSERT INTO t VALUES (1)")
        r.execute("INSERT INTO secret VALUES (1)")
        r.access_control = FileBasedAccessControl(
            [{"user": "u", "table": "t", "privileges":
              ["SELECT", "INSERT", "DELETE", "UPDATE"]}]
        )
        with pytest.raises(AccessDeniedError):
            r.execute("DELETE FROM t WHERE x IN (SELECT v FROM secret)")
        with pytest.raises(AccessDeniedError):
            r.execute("UPDATE t SET x = 2 WHERE x IN (SELECT v FROM secret)")

    def test_update_unknown_column(self):
        from trino_tpu.sql.analyzer import AnalysisError

        r = self._runner()
        with pytest.raises(AnalysisError):
            r.execute("UPDATE t SET nope = 1")

    def test_dml_rejected_in_explicit_transaction(self):
        from trino_tpu.transaction import TransactionError

        r = self._runner()
        r.execute("START TRANSACTION")
        with pytest.raises(TransactionError):
            r.execute("DELETE FROM t WHERE x = 1")
        r.execute("ROLLBACK")

    def test_access_control_gates_delete(self):
        from trino_tpu.connectors.memory import create_memory_connector
        from trino_tpu.security import AccessDeniedError, FileBasedAccessControl

        ac = FileBasedAccessControl(
            [{"user": "u", "privileges": ["SELECT", "INSERT", "OWNERSHIP"]}]
        )
        # note: OWNERSHIP implies all, so use a SELECT-only user
        ac2 = FileBasedAccessControl([{"user": "u", "privileges": ["SELECT"]}])
        r = LocalQueryRunner(
            Session(catalog="memory", schema="s", user="u"), access_control=ac
        )
        r.register_catalog("memory", create_memory_connector())
        r.execute("CREATE TABLE t (x bigint)")
        r.access_control = ac2
        with pytest.raises(AccessDeniedError):
            r.execute("DELETE FROM t WHERE x = 1")
