"""Observability + dynamic filtering: EXPLAIN ANALYZE operator stats,
tracing spans, event listeners, probe pruning (SURVEY.md §5.1, §5.5,
§5.6)."""

import pytest

from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime.events import EventListener


@pytest.fixture(scope="module")
def runner(tpch_local):
    return tpch_local


def test_explain_analyze_stats(runner):
    out = runner.execute(
        "EXPLAIN ANALYZE select n_regionkey, count(*) from nation"
        " group by n_regionkey order by 1"
    ).only_value()
    assert "Aggregate" in out  # the plan
    assert "HashAggregationOperator" in out  # the stats
    assert "in=25 rows" in out  # scan row count reached the stats
    assert "wall=" in out


def test_event_listener_lifecycle(runner):
    events = []

    class L(EventListener):
        def query_created(self, e):
            events.append(("created", e.query_id))

        def query_completed(self, e):
            events.append(("completed", e.state, e.rows))

    runner.event_listeners.add(L())
    runner.execute("select count(*) from region")
    assert events[0][0] == "created"
    assert events[1][:2] == ("completed", "finished")
    assert events[1][2] == 1

    class Broken(EventListener):
        def query_created(self, e):
            raise ValueError("boom")

    runner.event_listeners.add(Broken())
    before = runner.event_listeners.dispatch_failures
    runner.execute("select count(*) from region")  # must not fail
    assert runner.event_listeners.dispatch_failures == before + 1


def test_event_listener_failure_state(runner):
    events = []

    class L(EventListener):
        def query_completed(self, e):
            events.append((e.state, e.failure))

    runner.event_listeners.add(L())
    with pytest.raises(Exception):
        runner.execute("select no_such_column from region")
    assert events and events[-1][0] == "failed"


def test_tracer_span_tree():
    from trino_tpu.runtime.tracing import (
        KIND_PHASE,
        KIND_QUERY,
        QueryTrace,
        check_span_invariants,
    )

    t = QueryTrace("q1")
    with t.span("query q1", KIND_QUERY, query_id="q1") as q:
        with q.child("analyze", KIND_PHASE):
            pass
        with q.child("execute", KIND_PHASE):
            pass
    export = t.export()
    assert check_span_invariants(export) == []
    spans = export["spans"]
    assert spans[0]["name"] == "query q1"
    assert spans[0]["attributes"]["query_id"] == "q1"
    assert [s["name"] for s in spans[1:]] == ["analyze", "execute"]
    assert all(s["parent_id"] == spans[0]["span_id"] for s in spans[1:])


def test_dynamic_filter_prunes_probe(runner):
    out = runner.execute(
        "EXPLAIN ANALYZE select count(*) from lineitem, orders"
        " where l_orderkey = o_orderkey and o_orderkey < 100"
    ).only_value()
    df_line = next(
        line for line in out.splitlines() if "DynamicFilterOperator" in line
    )
    # the build-side domain now lands on the probe SCAN as a runtime
    # ColumnConstraint (PR 13), so pruning happens upstream of the
    # DynamicFilterOperator: the scan emits only the 98 matching rows
    # instead of the full 60064-row table
    assert "in=98 rows" in df_line
    assert "out=98 rows" in df_line
    scan_line = next(
        line for line in out.splitlines()
        if "TableScanOperator" in line and "out=98 rows" in line
    )
    assert scan_line


def test_dynamic_filter_correctness(runner):
    # anti join must NOT be pruned; inner matches un-filtered result
    r_off = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r_off.register_catalog("tpch", create_tpch_connector())
    q = (
        "select count(*) from lineitem, orders"
        " where l_orderkey = o_orderkey and o_totalprice > 100000"
    )
    from trino_tpu.sql.local_planner import LocalPlanner  # noqa: F401

    assert runner.execute(q).rows == r_off.execute(q).rows
