"""r4 function-breadth batch 3: sketch functions (HyperLogLog/TDigest on
the varchar carrier), regexp array functions, format, array folds, and
the SHOW FUNCTIONS catalog gate (VERDICT r3 item 7: >= 400 rows)."""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session

N = 5000


@pytest.fixture(scope="module")
def runner():
    conn = MemoryConnector()
    rng = np.random.default_rng(0)
    g = rng.integers(0, 3, N).astype(np.int64)
    v = rng.integers(0, 1000, N).astype(np.int64)
    conn.load_table(
        "default", "t",
        [ColumnMetadata("g", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [g, v],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", conn)
    return r, g, v


def one(r, sql):
    return r.execute(sql).rows[0][0]


class TestHyperLogLog:
    def test_grouped_estimate_within_error(self, runner):
        r, g, v = runner
        import pandas as pd

        true = pd.DataFrame({"g": g, "v": v}).groupby("g").v.nunique()
        rows = r.execute("select g, cardinality(approx_set(v)) "
                         "from t group by g order by g").rows
        for (grp, est) in rows:
            t = true[grp]
            assert abs(est - t) / t < 0.05  # p=12 -> ~1.6% stderr

    def test_merge_of_group_sketches(self, runner):
        r, g, v = runner
        est = one(r, "select cardinality(merge(s)) from "
                     "(select approx_set(v) s from t group by g)")
        true = len(set(v.tolist()))
        assert abs(est - true) / true < 0.05

    def test_empty_approx_set(self, runner):
        r, _, _ = runner
        assert one(r, "select cardinality(empty_approx_set())") == 0

    def test_digest_is_inspectable(self, runner):
        r, _, _ = runner
        assert one(r, "select approx_set(v) from t").startswith("hll:")


class TestTDigest:
    def test_median(self, runner):
        r, _, v = runner
        got = one(r, "select value_at_quantile(tdigest_agg(v), 0.5) from t")
        assert abs(got - float(np.median(v))) < 15

    def test_tail_quantile(self, runner):
        r, _, v = runner
        got = one(r, "select value_at_quantile(tdigest_agg(v), 0.99) from t")
        assert abs(got - float(np.quantile(v, 0.99))) < 15

    def test_merge_of_group_digests(self, runner):
        r, _, v = runner
        got = one(r, "select value_at_quantile(merge(d), 0.5) from "
                     "(select tdigest_agg(v) d from t group by g)")
        assert abs(got - float(np.median(v))) < 20

    def test_quantile_at_value_roundtrip(self, runner):
        r, _, v = runner
        q = one(r, "select quantile_at_value(tdigest_agg(v), 500.0) from t")
        assert abs(q - 0.5) < 0.03

    def test_accessor_over_table_column(self, runner):
        r, _, v = runner
        conn = MemoryConnector()
        digest = one(r, "select tdigest_agg(v) from t")
        conn.load_table("default", "d", [ColumnMetadata("d", T.VARCHAR)],
                        [[digest]])
        r2 = LocalQueryRunner(Session(catalog="m2", schema="default"))
        r2.register_catalog("m2", conn)
        got = one(r2, "select value_at_quantile(d, 0.5) from d")
        assert abs(got - float(np.median(v))) < 15


class TestRegexpArrays:
    def test_regexp_split(self, runner):
        r, _, _ = runner
        assert one(r, "select regexp_split('a1b22c', '[0-9]+')") == \
            ["a", "b", "c"]

    def test_regexp_extract_all(self, runner):
        r, _, _ = runner
        assert one(r, "select regexp_extract_all('a1b22c333', '[0-9]+')") \
            == ["1", "22", "333"]
        assert one(r, "select regexp_extract_all('a1b2', '([a-z])[0-9]', 1)"
                   ) == ["a", "b"]

    def test_no_match_is_empty_array(self, runner):
        r, _, _ = runner
        assert one(r, "select cardinality("
                      "regexp_extract_all('xyz', '[0-9]'))") == 0


class TestMiscBreadth:
    def test_format(self, runner):
        r, _, _ = runner
        assert one(r, "select format('%s=%d (%.1f%%)', 'x', 7, 2.5)") == \
            "x=7 (2.5%)"

    def test_contains_sequence(self, runner):
        r, _, _ = runner
        assert one(r, "select contains_sequence(array[1,2,3,4], "
                      "array[2,3])") is True
        assert one(r, "select contains_sequence(array[1,2,3,4], "
                      "array[2,4])") is False

    def test_shuffle_permutes(self, runner):
        r, _, _ = runner
        got = one(r, "select array_sort(shuffle(array[3,1,2]))")
        assert got == [1, 2, 3]

    def test_array_reverse_and_concat(self, runner):
        r, _, _ = runner
        assert one(r, "select reverse(array[1,2,3])") == [3, 2, 1]
        assert one(r, "select concat(array[1,2], array[3])") == [1, 2, 3]

    def test_date_format_and_to_char(self, runner):
        r, _, _ = runner
        assert one(r, "select date_format(timestamp '2020-05-06 07:08:09'"
                      ", '%Y-%m-%d %H:%i:%s')") == "2020-05-06 07:08:09"
        assert one(r, "select to_char(date '2021-02-03', 'yyyy/mm/dd')") \
            == "2021/02/03"

    def test_map_keys_values_registered(self, runner):
        r, _, _ = runner
        rows = r.execute("show functions").rows
        names = {row[0] for row in rows}
        assert {"map_keys", "map_values", "regexp_split", "approx_set",
                "tdigest_agg", "merge", "nth_value"} <= names


def test_show_functions_meets_target(runner):
    """VERDICT r3 item 7: SHOW FUNCTIONS >= 400 rows. Rows are the
    reference's unit — one per callable name, alias, and concrete
    per-type overload (registry.FunctionMetadata.overloads)."""
    r, _, _ = runner
    assert len(r.execute("show functions").rows) >= 400
