"""Stats propagation + cost-based decisions (main/cost/ analogue,
SURVEY.md §2.2): estimates vs actual row counts, broadcast decisions,
adaptive partition counts."""

import pytest

from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.sql.analyzer import Analyzer
from trino_tpu.sql.fragmenter import plan_distributed
from trino_tpu.sql.parser import parse
from trino_tpu.sql.stats import StatsCalculator, determine_partition_count


@pytest.fixture(scope="module")
def catalogs():
    c = CatalogManager()
    c.register("tpch", create_tpch_connector())
    return c


@pytest.fixture(scope="module")
def estimator(catalogs):
    an = Analyzer(catalogs, "tpch", "tiny")
    calc = StatsCalculator(catalogs)

    def est(sql: str) -> float:
        return calc.stats(an.plan(parse(sql))).row_count

    return est


# (sql, actual rows at tiny/sf0.01, allowed relative error)
CASES = [
    ("select * from lineitem", 60064, 0.01),
    ("select * from orders", 15000, 0.01),
    (
        "select * from lineitem where l_shipdate <= date '1998-09-02'",
        59144, 0.10,
    ),
    ("select * from lineitem where l_quantity < 24", 27885, 0.10),
    (
        "select * from orders, customer where o_custkey = c_custkey",
        15000, 0.05,
    ),
    (
        "select * from lineitem, orders where l_orderkey = o_orderkey",
        60064, 0.05,
    ),
    (
        "select l_returnflag, count(*) from lineitem group by l_returnflag",
        3, 0.01,
    ),
    (
        "select l_orderkey, count(*) from lineitem group by l_orderkey",
        15000, 0.05,
    ),
]


@pytest.mark.parametrize("sql,actual,tol", CASES)
def test_estimate_accuracy(sql, actual, tol, estimator):
    est = estimator(sql)
    assert abs(est - actual) <= max(actual * tol, 2), (est, actual)


def test_determine_partition_count():
    assert determine_partition_count(100, 64) == 1
    assert determine_partition_count(3.2e6, 64) == 4
    assert determine_partition_count(1e12, 64) == 64


def test_broadcast_vs_partitioned(catalogs):
    an = Analyzer(catalogs, "tpch", "tiny")
    # nation build side (25 rows) -> broadcast
    sp = plan_distributed(
        an.plan(parse(
            "select * from supplier, nation where s_nationkey = n_nationkey"
        )),
        catalogs,
    )
    assert "broadcast" in {f.output_kind for f in sp.all_fragments()}
    # force partitioned with a tiny threshold
    sp2 = plan_distributed(
        an.plan(parse(
            "select * from supplier, nation where s_nationkey = n_nationkey"
        )),
        catalogs,
        broadcast_threshold=10,
    )
    hash_outs = [f for f in sp2.all_fragments() if f.output_kind == "hash"]
    assert len(hash_outs) == 2  # both sides repartitioned


def test_suggested_partitions_annotated(catalogs):
    an = Analyzer(catalogs, "tpch", "tiny")
    sp = plan_distributed(
        an.plan(parse(
            "select l_orderkey, sum(l_quantity) from lineitem group by l_orderkey"
        )),
        catalogs,
    )
    hash_frags = [f for f in sp.all_fragments() if f.partitioning == "hash"]
    assert hash_frags and all(
        f.suggested_partitions is not None for f in hash_frags
    )


def test_explain_analyze_device_inclusive_attribution():
    """EXPLAIN ANALYZE closes every timed section with a device barrier so
    per-operator walls INCLUDE device time (VERDICT r4 weak #2: stats
    previously measured host dispatch only, with the final sync
    mis-attributed to the sink)."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import LocalQueryRunner, Session

    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    text = r.execute(
        "explain analyze select l_returnflag, sum(l_quantity) "
        "from lineitem group by l_returnflag"
    ).rows[0][0]
    assert "DEVICE-INCLUSIVE" in text
    # the heavy work must land on scan/aggregate, not the sink
    import re

    walls = {}
    for m in re.finditer(r"(\w+Operator|CollectorSink): .*wall=([0-9.]+)ms", text):
        walls[m.group(1)] = max(
            walls.get(m.group(1), 0.0), float(m.group(2))
        )
    assert walls.get("CollectorSink", 0.0) <= max(
        walls.get("HashAggregationOperator", 0.0),
        walls.get("TableScanOperator", 0.0),
    ), walls
