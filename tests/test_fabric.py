"""Multi-host replica fabric (PR 19): checkpoint transport, membership
epochs, warm join.

runtime/fabric.py is the wire PR 17's host-portable checkpoints were
missing: `CheckpointPusher` ships export_bytes payloads to peer
coordinators over runtime/http.py (sha256 content digest verified
before the receiver's generation-fenced import_bytes), `Fabric.
try_pull` fetches them back on demand at failover, and the membership
tier (ReplicaManager.leave/join under a monotonic epoch, driven by the
NodeManager heartbeat listeners) decides who the peers ARE. These
tests pin:

  - the push/pull round trip across a REAL process boundary: a
    subprocess FabricServer receives pushed bytes and serves them back
    byte-identically, digests intact;
  - digest verification at the receive side: a corrupted or truncated
    payload is refused before import_bytes (the store stays clean) and
    an undecodable key is refused the same way;
  - membership epochs: leave/join advance the epoch monotonically, a
    resume targeting a replica whose epoch moved (or which is out of
    the pool) is refused with the typed MembershipEpochError, and the
    fence counts into fabric.epoch_fences;
  - the exactly-one-owner ledger across a flap: a second claim on an
    owned query is refused even after the owner LEFT (its chunk loop
    may still be unwinding), and only an unclaim frees the query;
  - backoff budget exhaustion: a dead peer spends the
    RequestErrorTracker budget and raises RequestFailedError from the
    client; Fabric.try_pull degrades to False (cold restart), never
    hangs;
  - push shedding: a full bounded queue sheds (fabric.push_sheds), the
    chunk loop's offer never blocks;
  - warm join: warm_manifest/apply_manifest round-trip the warm-class
    census so a joining host proves the classes warm before placement,
    and warm_join_replay applies a peer manifest without raising;
  - the heartbeat bridge: MembershipDriver turns node state
    transitions into replica leave/join with the warm replay run
    before the rejoin enters the pool.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from trino_tpu.recovery.checkpoint import MeshCheckpoint, MeshCheckpointStore
from trino_tpu.runtime.error_tracker import RequestFailedError, RetryPolicy
from trino_tpu.runtime.fabric import (
    CheckpointPusher,
    Fabric,
    HostFabric,
    MembershipDriver,
    MembershipEpochError,
    checkpoint_digest,
    decode_key,
    encode_key,
    fabric_status,
    warm_join_manifest,
    warm_join_replay,
)
from trino_tpu.runtime.http import FabricClient, FabricServer
from trino_tpu.runtime.replicas import ReplicaManager

SECRET = "test-fabric-secret"


def fake_devices(n):
    return [f"fake-dev-{i}" for i in range(n)]


def make_checkpoint(tag="fabric", chunk=3):
    """A host-portable checkpoint with numpy carries — the same leaf
    types a real mesh run snapshots (tables=() skips the generation
    fence: transport, not staleness, is under test here)."""
    key = ("fabric-test", tag)
    ckpt = MeshCheckpoint(
        next_chunk=chunk, n_chunks=8, chunk_cap=64,
        resolved_caps={"rows": 64},
        carries_host=(np.arange(64, dtype=np.int64),
                      np.linspace(0.0, 1.0, 64)),
        tables=(), generations=(),
    )
    return key, ckpt


# -- wire helpers -----------------------------------------------------


def test_key_codec_round_trip():
    key = ("q", 7, ("a", "b"), frozenset({1, 2}))
    assert decode_key(encode_key(key)) == key


def test_key_codec_rejects_non_tuple():
    import base64
    import pickle

    ekey = base64.urlsafe_b64encode(pickle.dumps(["not", "a", "tuple"]))
    with pytest.raises(TypeError):
        decode_key(ekey.decode("ascii"))


# -- subprocess round trip --------------------------------------------

_CHILD = """
import sys
from trino_tpu.recovery.checkpoint import MeshCheckpointStore
from trino_tpu.runtime.fabric import HostFabric
from trino_tpu.runtime.http import FabricServer

store = MeshCheckpointStore()
srv = FabricServer(
    HostFabric(store=store, host_id="child"),
    internal_secret={secret!r},
)
print(srv.port, flush=True)
sys.stdin.read()  # serve until the parent closes our stdin
"""


@pytest.fixture
def child_server():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PYTHONPATH", os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(secret=SECRET)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        env=env, cwd=env["PYTHONPATH"], text=True,
    )
    try:
        line = proc.stdout.readline().strip()
        assert line, "child FabricServer never printed its port"
        yield f"http://127.0.0.1:{int(line)}"
    finally:
        try:
            proc.stdin.close()
        except Exception:
            pass
        proc.terminate()
        proc.wait(timeout=10)


def test_push_pull_round_trip_across_process_boundary(child_server):
    """Push real checkpoint bytes into a SUBPROCESS coordinator's store
    and pull them back byte-identically — the fabric's reason to
    exist. The child's counters see exactly one receive and one
    serve."""
    store = MeshCheckpointStore()
    key, ckpt = make_checkpoint("xproc")
    store.put(key, ckpt)
    data = store.export_bytes(key)
    assert data is not None

    client = FabricClient(child_server, internal_secret=SECRET)
    out = client.push_checkpoint(key, data)
    assert out == {"imported": True}

    pulled, digest = client.pull_checkpoint(key)
    assert pulled == data
    assert digest == checkpoint_digest(data)

    st = client.status()
    assert st["received"] == 1 and st["served"] == 1
    assert st["digest_rejects"] == 0

    # and the full Fabric pull path lands it in a cleared local store
    local = MeshCheckpointStore()
    fab = Fabric([child_server], store=local, internal_secret=SECRET)
    try:
        assert fab.try_pull(key) is True
        got = local.get(key)
        assert got is not None and got.next_chunk == ckpt.next_chunk
        np.testing.assert_array_equal(
            got.carries_host[0], ckpt.carries_host[0]
        )
    finally:
        fab.stop()


def test_pull_of_absent_key_is_none_not_error(child_server):
    client = FabricClient(child_server, internal_secret=SECRET)
    data, digest = client.pull_checkpoint(("fabric-test", "never-pushed"))
    assert data is None and digest is None


# -- digest verification at the receive side --------------------------


def test_receive_rejects_corrupt_and_truncated_payloads():
    """Bit-flipped or truncated bytes under the original digest never
    reach import_bytes; a truncated payload under a MATCHING digest is
    refused by import_bytes itself (undecodable). The store stays
    empty either way — corruption degrades to restart, not poison."""
    store = MeshCheckpointStore()
    fab = HostFabric(store=store, host_id="t")
    src = MeshCheckpointStore()
    key, ckpt = make_checkpoint("corrupt")
    src.put(key, ckpt)
    data = src.export_bytes(key)
    digest = checkpoint_digest(data)

    flipped = bytearray(data)
    flipped[len(flipped) // 2] ^= 0xFF
    out = fab.receive_checkpoint(encode_key(key), bytes(flipped), digest)
    assert out == {"imported": False, "reason": "digest_mismatch"}

    cut = data[: len(data) // 2]
    out = fab.receive_checkpoint(encode_key(key), cut, digest)
    assert out == {"imported": False, "reason": "digest_mismatch"}

    # matching digest over truncated bytes: the digest gate passes but
    # import_bytes refuses the undecodable payload
    out = fab.receive_checkpoint(encode_key(key), cut, checkpoint_digest(cut))
    assert out["imported"] is False

    out = fab.receive_checkpoint("!!not-base64!!", data, digest)
    assert out == {"imported": False, "reason": "bad_key"}

    assert len(store) == 0
    assert fab.digest_rejects >= 2


# -- membership epochs ------------------------------------------------


def test_leave_join_advance_epoch_and_fence_resume():
    """A flap (leave + rejoin) advances the epoch twice; a resume
    carrying the pre-flap epoch is refused with the typed error naming
    both epochs, and the fence is counted."""
    rm = ReplicaManager(2, devices=fake_devices(4))
    rep = rm.replicas[0]
    epoch0 = rm.membership_epoch
    rm.require_epoch(rep, epoch0)  # same epoch: passes

    rm.leave(0)
    assert rm.membership_epoch == epoch0 + 1
    rm.leave(0)  # idempotent: no double-advance
    assert rm.membership_epoch == epoch0 + 1
    rm.join(0)
    assert rm.membership_epoch == epoch0 + 2
    assert rep.join_epoch == rm.membership_epoch

    with pytest.raises(MembershipEpochError) as ei:
        rm.require_epoch(rep, epoch0)
    assert ei.value.replica_id == 0
    assert ei.value.expected_epoch == epoch0
    assert ei.value.actual_epoch == rep.join_epoch
    assert rm.epoch_fences == 1

    # a replica OUT of the pool is fenced even at the current epoch
    rm.leave(1)
    with pytest.raises(MembershipEpochError):
        rm.require_epoch(rm.replicas[1], rm.membership_epoch)
    assert rm.joins == 1 and rm.leaves == 2


def test_flap_keeps_breaker_state():
    """The Replica object survives leave/join, so a flap never resets
    health history (a flapping host must not launder its breaker)."""
    rm = ReplicaManager(2, devices=fake_devices(4),
                        breaker_threshold=2, breaker_cooldown_s=60.0)
    rep = rm.replicas[0]
    rm.report_failure(rep)
    rm.report_failure(rep)
    assert rep.breaker.is_open
    rm.leave(0)
    rm.join(0)
    assert rm.replicas[0] is rep
    assert rep.breaker.is_open


def test_membership_line_counts():
    rm = ReplicaManager(2, devices=fake_devices(4))
    rm.leave(1)
    rm.join(1)
    assert rm.claim("q-line", rm.replicas[0])
    line = rm.membership_line()
    assert line.startswith(f"membership= epoch={rm.membership_epoch} ")
    assert "joins=1" in line and "leaves=1" in line
    assert "owners=1" in line


# -- exactly-one-owner ledger -----------------------------------------


def test_flap_never_double_places_a_query():
    """While one replica's claim is live, a sibling's claim is refused
    — even after the owner LEFT (its chunk loop may still be
    unwinding). Only the owner's unclaim frees the query."""
    rm = ReplicaManager(2, devices=fake_devices(4))
    r0, r1 = rm.replicas
    assert rm.claim("q1", r0) is True
    assert rm.claim("q1", r0) is True  # same-owner refresh: no-op
    assert rm.claim("q1", r1) is False

    rm.leave(0)  # the owner flaps out; its claim must survive
    assert rm.claim("q1", r1) is False
    rm.unclaim("q1", r1)  # non-owner unclaim is a no-op
    assert rm.owner_of("q1") == (0, 1)

    rm.unclaim("q1", r0)
    assert rm.owner_of("q1") is None
    assert rm.claim("q1", r1) is True
    assert rm.owner_of("q1")[0] == 1

    assert rm.claim("", r0) is True  # anonymous dispatch: nothing to fence


# -- backoff budget exhaustion ----------------------------------------

_DEAD_PEER = "http://127.0.0.1:9"  # discard port: nothing listens
_FAST_RETRY = RetryPolicy(
    max_error_duration_s=0.2, min_backoff_s=0.01, max_backoff_s=0.05
)


def test_client_budget_exhaustion_raises_typed_error():
    client = FabricClient(
        _DEAD_PEER, timeout=0.2, internal_secret=SECRET,
        retry_policy=_FAST_RETRY,
    )
    key, _ = make_checkpoint("dead")
    with pytest.raises(RequestFailedError):
        client.push_checkpoint(key, b"payload")
    with pytest.raises(RequestFailedError):
        client.pull_checkpoint(key)


def test_try_pull_degrades_to_false_on_dead_peer():
    """A spent budget on every peer means try_pull returns False — the
    coordinator restarts cold; it never hangs or raises out of the
    failover path."""
    store = MeshCheckpointStore()
    fab = Fabric([_DEAD_PEER], store=store, internal_secret=SECRET,
                 max_error_duration_s=0.2)
    try:
        key, _ = make_checkpoint("deadpull")
        t0 = time.monotonic()
        assert fab.try_pull(key) is False
        assert time.monotonic() - t0 < 5.0
        assert len(store) == 0
    finally:
        fab.stop()


def test_push_failure_after_budget_is_dropped_not_raised():
    """The pusher thread swallows a spent budget (push is best-effort:
    the receiver can still pull on demand) and counts it."""
    store = MeshCheckpointStore()
    key, ckpt = make_checkpoint("dropped")
    store.put(key, ckpt)
    fab = Fabric([_DEAD_PEER], store=store, internal_secret=SECRET,
                 max_error_duration_s=0.2)
    try:
        assert fab.pusher.offer(key) is True
        assert fab.pusher.flush(10.0) is True
        assert fab.pusher.pushes == 0
        assert fab.pusher.push_failures == 1
    finally:
        fab.stop()


# -- push shedding ----------------------------------------------------


def test_full_queue_sheds_never_blocks():
    class _SlowClient:
        def __init__(self, gate):
            self.gate = gate

        def push_checkpoint(self, key, data, digest=None):
            self.gate.wait(5.0)
            return {"imported": True}

    store = MeshCheckpointStore()
    key, ckpt = make_checkpoint("shed")
    store.put(key, ckpt)
    gate = threading.Event()
    pusher = CheckpointPusher(store, [_SlowClient(gate)], depth=1)
    try:
        # first offer occupies the worker, second fills the depth-1
        # queue, the rest must shed immediately
        deadline = time.monotonic() + 5.0
        while pusher.queued() == 0 and time.monotonic() < deadline:
            pusher.offer(key)
        sheds0 = pusher.sheds
        while pusher.offer(key) and time.monotonic() < deadline:
            pass
        assert pusher.sheds > sheds0 or pusher.sheds > 0
    finally:
        gate.set()
        pusher.stop()


# -- warm join --------------------------------------------------------


def test_warm_manifest_round_trip(monkeypatch):
    from trino_tpu.compile import warmup
    from trino_tpu.parallel import mesh_chunk

    keys = {
        ("hash_agg", 1024, ("int64", "float64")),
        ("join_probe", 4096, ("int64",)),
    }
    warmup.note_classes_warm(keys)
    manifest = warm_join_manifest()
    assert isinstance(manifest["classes"], list)
    assert isinstance(manifest["programs"], list)
    sent = {
        (op, cap, tuple(dts)) for op, cap, dts in manifest["classes"]
    }
    assert keys <= sent

    # the joining "host": a cleared registry, the peer's manifest, no
    # local census entries to replay
    warmup.reset_warm_classes()
    assert warmup.classes_warm(keys) is False
    monkeypatch.setattr(mesh_chunk, "mesh_warmup_entries", lambda: [])
    applied = warm_join_replay(manifest)
    assert applied >= len(keys)
    assert warmup.classes_warm(keys) is True


def test_apply_manifest_skips_malformed_items():
    from trino_tpu.compile.warmup import apply_manifest

    n = apply_manifest(
        [["agg", 64, ["int64"]], "garbage", [1], None, ["op"]]
    )
    assert n == 1
    assert apply_manifest(None) == 0


def test_join_runs_warm_before_pool_entry():
    rm = ReplicaManager(2, devices=fake_devices(4))
    rm.leave(0)
    order = []

    def warm():
        # the replica must NOT yet be back in the pool while warming
        order.append(rm.replicas[0].state)

    rm.join(0, warm=warm)
    assert order == ["left"]
    assert rm.replicas[0].state == "active"

    rm.leave(1)

    def bad_warm():
        order.append("warm-raised")
        raise RuntimeError("warmup exploded")

    rm.join(1, warm=bad_warm)  # warm failure delays, never gates
    assert rm.replicas[1].state == "active"


# -- heartbeat bridge -------------------------------------------------


def test_membership_driver_bridges_node_states():
    """Heartbeat state transitions drive replica leave/join: a replica
    host going failed leaves the pool (epoch advances), coming back
    active rejoins AFTER the warm replay; non-replica workers are
    ignored."""
    from trino_tpu.runtime.discovery import NodeManager

    nm = NodeManager(ping_interval=30.0)
    rm = ReplicaManager(2, devices=fake_devices(4))
    warmed = []
    MembershipDriver(
        nm, rm,
        replica_of=lambda wid: {"w0": 0, "w1": 1}.get(wid),
        warm=lambda: warmed.append(1),
    )
    epoch0 = rm.membership_epoch

    nm._notify_state("w0", "active", "failed")
    assert rm.replicas[0].state == "left"
    assert rm.membership_epoch == epoch0 + 1

    nm._notify_state("w0", "failed", "active")
    assert rm.replicas[0].state == "active"
    assert warmed == [1]
    assert rm.membership_epoch == epoch0 + 2

    nm._notify_state("coordinator-only", "active", "failed")  # not a replica
    nm._notify_state("w1", "active", "active")  # no transition
    assert rm.membership_epoch == epoch0 + 2
    assert rm.replicas[1].state == "active"


def test_fabric_status_counters_registered():
    st = fabric_status()
    for name in ("pushes", "pulls", "push_sheds", "digest_rejects",
                 "joins", "leaves", "epoch_fences", "attached"):
        assert name in st
