"""MERGE — VERDICT r4 item #10 (parser/sql/tree/Merge.java).

Planned as a read-rewrite: survivors (target LEFT JOIN source, first
matching WHEN MATCHED arm per row) plus inserts (NOT EXISTS anti join,
first matching WHEN NOT MATCHED arm), with Trino's multiple-match
cardinality error. Oracle: hand-computed upsert matrices."""

import pytest

from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.connectors.memory import create_memory_connector


@pytest.fixture()
def r():
    r = LocalQueryRunner(Session(catalog="memory", schema="t"))
    r.register_catalog("memory", create_memory_connector())
    r.execute("create table memory.t.tgt (id bigint, v varchar, amt double)")
    r.execute(
        "insert into tgt values (1, 'a', 10.0), (2, 'b', 20.0), "
        "(3, 'c', 30.0)"
    )
    r.execute("create table memory.t.src (id bigint, v varchar, amt double)")
    r.execute(
        "insert into src values (2, 'B', 200.0), (3, 'C', -1.0), "
        "(4, 'd', 40.0), (5, 'e', 50.0)"
    )
    return r


def rows(r):
    return sorted(r.execute("select id, v, amt from tgt").rows)


class TestMergeMatrix:
    def test_full_upsert(self, r):
        res = r.execute(
            "merge into tgt t using src s on t.id = s.id "
            "when matched and s.amt < 0 then delete "
            "when matched then update set v = s.v, amt = s.amt "
            "when not matched then insert (id, v, amt) "
            "values (s.id, s.v, s.amt)"
        )
        # 2 matched (one deleted, one updated) + 2 inserted
        assert res.rows == [[4]]
        assert rows(r) == [
            [1, "a", 10.0], [2, "B", 200.0],
            [4, "d", 40.0], [5, "e", 50.0],
        ]

    def test_clause_order_first_match_wins(self, r):
        r.execute(
            "merge into tgt t using src s on t.id = s.id "
            "when matched and s.amt < 0 then delete "
            "when matched then update set amt = s.amt "
            "when not matched and s.amt > 45 then insert (id, v, amt) "
            "values (s.id, s.v, s.amt)"
        )
        assert rows(r) == [
            [1, "a", 10.0], [2, "b", 200.0], [5, "e", 50.0]
        ]

    def test_update_only(self, r):
        res = r.execute(
            "merge into tgt t using src s on t.id = s.id "
            "when matched then update set amt = t.amt + s.amt"
        )
        assert res.rows == [[2]]
        assert rows(r) == [
            [1, "a", 10.0], [2, "b", 220.0], [3, "c", 29.0]
        ]

    def test_delete_only(self, r):
        res = r.execute(
            "merge into tgt t using src s on t.id = s.id "
            "when matched then delete"
        )
        assert res.rows == [[2]]
        assert rows(r) == [[1, "a", 10.0]]

    def test_insert_only_with_default_null(self, r):
        res = r.execute(
            "merge into tgt t using src s on t.id = s.id "
            "when not matched then insert (id) values (s.id)"
        )
        assert res.rows == [[2]]
        assert rows(r)[-2:] == [[4, None, None], [5, None, None]]

    def test_subquery_source(self, r):
        res = r.execute(
            "merge into tgt t using "
            "(select id, amt * 2 as amt2 from src where amt > 0) s "
            "on t.id = s.id "
            "when matched then update set amt = s.amt2 "
            "when not matched then insert (id, amt) values (s.id, s.amt2)"
        )
        assert res.rows == [[3]]
        assert rows(r) == [
            [1, "a", 10.0], [2, "b", 400.0], [3, "c", 30.0],
            [4, None, 80.0], [5, None, 100.0],
        ]

    def test_multiple_match_is_error(self, r):
        r.execute("create table memory.t.dup (id bigint)")
        r.execute("insert into dup values (2), (2)")
        with pytest.raises(RuntimeError, match="more than one source row"):
            r.execute(
                "merge into tgt t using dup s on t.id = s.id "
                "when matched then delete"
            )
        # target unchanged after the failed statement
        assert rows(r) == [
            [1, "a", 10.0], [2, "b", 20.0], [3, "c", 30.0]
        ]

    def test_no_matches_noop(self, r):
        res = r.execute(
            "merge into tgt t using (select id from src where id > 100) s "
            "on t.id = s.id when matched then delete"
        )
        assert res.rows == [[0]]
        assert len(rows(r)) == 3


class TestScaledWriters:
    """Writer scale-out with observed volume (SystemPartitioningHandle
    SCALED_WRITER_* + ScaledWriterScheduler) — counter-asserted."""

    def test_large_write_scales_out(self):
        from trino_tpu.exec.operators import ScaledWriterSink

        r = LocalQueryRunner(
            Session(catalog="memory", schema="t", batch_rows=1 << 16,
                    task_concurrency=4)
        )
        r.register_catalog("memory", create_memory_connector())
        r.execute("create table memory.t.small (x bigint)")
        r.execute("create table memory.t.big2 (x bigint)")
        before = dict(ScaledWriterSink.COUNTERS)
        # small write: one writer
        r.execute("insert into small values (1), (2)")
        assert ScaledWriterSink.COUNTERS["scale_ups"] == before["scale_ups"]
        # integration: a bulk insert routes through the scaled sink
        r.execute(
            "insert into big2 select x from unnest(sequence(1, 5000)) "
            "as u(x)"
        )
        assert r.execute("select count(*) from big2").rows == [[5000]]
        # volume-based scaling needs real volume; drive the sink
        # directly for a deterministic assert
        made = []
        class FakeSink:
            def __init__(self):
                made.append(self)
                self.rows = 0
            def append(self, b):
                self.rows += b.capacity
            def finish(self):
                return self.rows
        class FakeBatch:
            capacity = 1 << 20
        s = ScaledWriterSink(FakeSink, max_writers=4, scale_rows=1 << 21)
        for _ in range(12):
            s.append(FakeBatch())
        total = s.finish()
        assert total == 12 * (1 << 20)
        assert len(made) > 1, "writer count never scaled"
        assert ScaledWriterSink.COUNTERS["max_writers"] >= len(made)


class TestInsertOnlyMultiMatch:
    def test_insert_only_merge_with_duplicate_matches(self):
        """Insert-only MERGE legally allows several source rows to
        match one target row; survivors must not fan out (r5 review)."""
        r = LocalQueryRunner(Session(catalog="memory", schema="t"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("create table memory.t.tg2 (k bigint, v bigint)")
        r.execute("insert into tg2 values (1, 100), (2, 200)")
        r.execute("create table memory.t.sr2 (k bigint, v bigint)")
        r.execute("insert into sr2 values (1, 111), (1, 112), (3, 300)")
        res = r.execute(
            "merge into tg2 using sr2 s on tg2.k = s.k "
            "when not matched then insert (k, v) values (s.k, s.v)"
        )
        assert res.rows == [[1]]
        assert sorted(r.execute("select k, v from tg2").rows) == [
            [1, 100], [2, 200], [3, 300]
        ]
