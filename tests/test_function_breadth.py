"""r3 function-breadth families (VERDICT r2 missing #6): bitwise, math
remainder, datetime, JSON, string remainder — each oracle-checked
against python/known values. Reference: BitwiseFunctions.java,
MathFunctions.java, DateTimeFunctions.java, JsonFunctions.java,
StringFunctions.java."""

import math

import pytest

from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", create_memory_connector())
    return r


def _one(runner, sql):
    return runner.execute(sql).rows[0]


def test_bitwise_family(runner):
    assert _one(runner, "select bitwise_and(19,25), bitwise_or(19,25),"
                " bitwise_xor(19,25), bitwise_not(19)") == [17, 27, 10, -20]
    assert _one(runner, "select bitwise_left_shift(1,3),"
                " bitwise_right_shift_arithmetic(-8,1)") == [8, -4]
    # logical right shift is zero-filling on the 64-bit pattern
    assert _one(runner, "select bitwise_right_shift(-8,1)") == [
        (-8 % (1 << 64)) >> 1
    ]
    assert _one(runner, "select bit_count(9), bit_count(-7, 64),"
                " bit_count(-7, 8)") == [2, 62, 6]


def test_math_remainder(runner):
    pi, e_, cot1 = _one(
        runner, "select pi(), e(), round(cot(1.0), 6)"
    )
    assert pi == pytest.approx(math.pi)
    assert e_ == pytest.approx(math.e)
    assert cot1 == pytest.approx(round(1 / math.tan(1.0), 6))
    assert _one(runner, "select is_nan(nan()), is_infinite(infinity())") \
        == [True, True]
    assert _one(
        runner,
        "select width_bucket(3.14, 0.0, 4.0, 3),"
        " width_bucket(-1.0, 0.0, 4.0, 3), width_bucket(9.9, 0.0, 4.0, 3)",
    ) == [3, 0, 4]
    cdf, inv = _one(
        runner,
        "select round(normal_cdf(0.0, 1.0, 1.96), 3),"
        " round(inverse_normal_cdf(0.0, 1.0, 0.975), 2)",
    )
    assert cdf == pytest.approx(0.975)
    assert inv == pytest.approx(1.96)


def test_datetime_breadth(runner):
    ts = "date_parse('2024-03-05 10:30:45', '%Y-%m-%d %H:%i:%s')"
    assert _one(
        runner,
        f"select hour({ts}), minute({ts}), second({ts}), year({ts})",
    ) == [10, 30, 45, 2024]
    assert _one(runner, "select hour(from_unixtime(3700)),"
                " minute(from_unixtime(3700))") == [1, 1]
    assert _one(runner, "select to_unixtime(from_unixtime(12.5))") == [12.5]
    # invalid text parses to NULL, not an error
    assert _one(
        runner, "select date_parse('nope', '%Y-%m-%d')"
    ) == [None]


def test_json_breadth(runner):
    runner.execute("create table jdoc (d varchar)")
    runner.execute(
        """insert into jdoc values ('{"a": [1, 2, {"b": 7}]}'),"""
        """ ('[1,2,3]'), ('"x"'), ('nope')"""
    )
    rows = runner.execute(
        "select json_extract(d, '$.a[2]'), is_json_scalar(d),"
        " json_array_contains(d, 2), json_array_get(d, 1),"
        " json_parse(d) from jdoc"
    ).rows
    assert rows == [
        ['{"b":7}', False, None, None, '{"a":[1,2,{"b":7}]}'],
        [None, False, True, "2", "[1,2,3]"],
        [None, True, None, None, '"x"'],
        [None, None, None, None, None],
    ]


def test_string_remainder(runner):
    runner.execute("create table sw (w varchar)")
    runner.execute("insert into sw values ('Robert'), ('Tymczak')")
    rows = runner.execute(
        "select soundex(w), regexp_position(w, 'm'), normalize(w) from sw"
    ).rows
    assert rows == [
        ["R163", -1, "Robert"],
        ["T522", 3, "Tymczak"],
    ]


def test_show_functions_breadth(runner):
    rows = runner.execute("SHOW FUNCTIONS").rows
    names = {r[0] for r in rows}
    for want in ("bitwise_and", "width_bucket", "json_extract",
                 "normal_cdf", "soundex", "from_unixtime", "bit_count"):
        assert want in names, want
    assert len(rows) >= 180, len(rows)
    assert "asinh" in names


# --- FULL OUTER JOIN (engine-wide; previously raised at analysis) ---


def test_full_outer_join(runner):
    runner.execute("create table fa (x bigint, p varchar)")
    runner.execute("insert into fa values (1,'a1'), (2,'a2'), (3,'a3')")
    runner.execute("create table fb (y bigint, q varchar)")
    runner.execute("insert into fb values (2,'b2'), (3,'b3'), (4,'b4')")
    rows = runner.execute(
        "select x, p, y, q from fa full outer join fb on x = y"
    ).rows
    key = lambda t: (t[0] is None, t[0] or 0, t[2] or 0)
    assert sorted(rows, key=key) == [
        [1, "a1", None, None],
        [2, "a2", 2, "b2"],
        [3, "a3", 3, "b3"],
        [None, None, 4, "b4"],
    ]
    # SELECT * follows declared order for RIGHT joins too
    assert runner.execute(
        "select * from fa right join fb on x = y order by y"
    ).rows == [
        [2, "a2", 2, "b2"],
        [3, "a3", 3, "b3"],
        [None, None, 4, "b4"],
    ]


def test_full_join_distributed_and_mesh():
    from trino_tpu.parallel import mesh_plan
    from trino_tpu.runtime import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="memory", schema="default"), n_workers=2,
        hash_partitions=2,
    )
    r.register_catalog("memory", create_memory_connector())
    r.execute("create table fa (x bigint)")
    r.execute("insert into fa values (1), (2), (3)")
    r.execute("create table fb (y bigint)")
    r.execute("insert into fb values (2), (3), (4)")
    before = mesh_plan.MESH_COUNTERS["queries"]
    res = r.execute("select x, y from fa full join fb on x = y")
    assert res.data_plane == "mesh"
    assert mesh_plan.MESH_COUNTERS["queries"] == before + 1
    key = lambda t: (t[0] is None, t[0] or 0, t[1] or 0)
    assert sorted(res.rows, key=key) == [
        [1, None], [2, 2], [3, 3], [None, 4],
    ]
