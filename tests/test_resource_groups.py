"""Resource-group admission accounting regressions (PR 3 satellite).

The invariant under test everywhere: a query that leaves the queue
WITHOUT being admitted — timeout, queue-cap rejection, or kill — must
release its queue slot and must NEVER have counted toward `running`.
The admission-timeout path (acquire's wait_for deadline) had no
coverage at all before these tests."""

import threading
import time

import pytest

from trino_tpu.runtime.resource_groups import (
    QueryKilledWhileQueuedError,
    QueryQueueFullError,
    ResourceGroupManager,
    ResourceGroupSpec,
)


def _mgr(max_concurrency: int = 1, max_queued: int = 10):
    return ResourceGroupManager(
        ResourceGroupSpec(
            "global", max_concurrency=max_concurrency, max_queued=max_queued
        )
    )


def test_admission_timeout_releases_queue_slot():
    mgr = _mgr()
    lease = mgr.acquire()
    assert mgr.stats()["global"] == (1, 0)
    with pytest.raises(QueryQueueFullError, match="timed out"):
        mgr.acquire(timeout=0.05)
    # the timed-out ticket fully unwound: nothing queued, nothing leaked
    assert mgr.stats()["global"] == (1, 0)
    mgr.release(lease)
    assert mgr.stats()["global"] == (0, 0)
    # and later admission still works (no phantom running count)
    lease2 = mgr.acquire(timeout=1)
    assert mgr.stats()["global"] == (1, 0)
    mgr.release(lease2)
    assert mgr.stats()["global"] == (0, 0)


def test_queue_cap_rejection_keeps_counters_clean():
    mgr = _mgr(max_concurrency=1, max_queued=1)
    lease = mgr.acquire()
    entered = threading.Event()
    admitted = []

    def second():
        entered.set()
        admitted.append(mgr.acquire(timeout=10))

    t = threading.Thread(target=second, daemon=True)
    t.start()
    entered.wait()
    deadline = time.monotonic() + 5
    while mgr.stats()["global"][1] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert mgr.stats()["global"] == (1, 1)
    with pytest.raises(QueryQueueFullError, match="full"):
        mgr.acquire(timeout=1)
    assert mgr.stats()["global"] == (1, 1)  # the rejection unwound itself
    mgr.release(lease)
    t.join(5)
    assert admitted
    assert mgr.stats()["global"] == (1, 0)
    mgr.release(admitted[0])
    assert mgr.stats()["global"] == (0, 0)


def test_killed_while_queued_releases_slot_and_never_runs():
    mgr = _mgr()
    lease = mgr.acquire()
    killed = threading.Event()
    errs = []

    def victim():
        try:
            mgr.acquire(timeout=30, cancelled=killed.is_set)
        except BaseException as e:
            errs.append(e)

    t = threading.Thread(target=victim, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while mgr.stats()["global"][1] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert mgr.stats()["global"] == (1, 1)
    killed.set()
    t.join(5)
    assert not t.is_alive()
    assert errs and isinstance(errs[0], QueryKilledWhileQueuedError), errs
    # the kill released the QUEUE slot and never touched `running`
    assert mgr.stats()["global"] == (1, 0)
    mgr.release(lease)
    assert mgr.stats()["global"] == (0, 0)


def test_kill_racing_admission_hands_slot_back():
    # kill lands while a slot is free: acquire notices the kill on the
    # already-granted ticket and gives the running slot straight back
    mgr = _mgr()
    with pytest.raises(QueryKilledWhileQueuedError):
        mgr.acquire(cancelled=lambda: True)
    assert mgr.stats()["global"] == (0, 0)
    lease = mgr.acquire()  # the handed-back slot is immediately usable
    mgr.release(lease)


def test_coordinator_delete_while_queued_releases_slot():
    """End to end over the client protocol: DELETE on a QUEUED query
    releases its admission slot, the query never executes, and the job
    reports the kill verdict."""
    import json as _json
    import urllib.request

    from trino_tpu import types as T
    from trino_tpu.engine import MaterializedResult
    from trino_tpu.runtime.server import CoordinatorServer

    release_slow = threading.Event()
    ran = []

    class StubRunner:
        def execute(self, sql, identity=None, transaction_id=None,
                    prepared=None):
            ran.append(sql)
            if sql == "slow":
                release_slow.wait(30)
            return MaterializedResult([[1]], ["x"], [T.BIGINT])

    mgr = _mgr()
    srv = CoordinatorServer(StubRunner(), resource_groups=mgr)
    try:
        def post(sql: str) -> dict:
            req = urllib.request.Request(
                srv.uri + "/v1/statement", data=sql.encode(), method="POST"
            )
            return _json.load(urllib.request.urlopen(req, timeout=10))

        def wait_stats(pred, what: str) -> None:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if pred(mgr.stats()["global"]):
                    return
                time.sleep(0.01)
            raise AssertionError(f"{what}: {mgr.stats()['global']}")

        post("slow")
        wait_stats(lambda s: s[0] == 1, "slow query never admitted")
        victim = post("victim")
        wait_stats(lambda s: s[1] == 1, "victim never queued")
        req = urllib.request.Request(
            srv.uri + f"/v1/statement/executing/{victim['id']}",
            method="DELETE",
        )
        urllib.request.urlopen(req, timeout=10)
        # the queue slot drains without the victim ever executing
        wait_stats(lambda s: s == (1, 0), "kill did not release the slot")
        assert ran == ["slow"]
        resp = _json.load(urllib.request.urlopen(
            srv.uri + f"/v1/statement/executing/{victim['id']}/0",
            timeout=10,
        ))
        assert resp["stats"]["state"] == "FAILED", resp
        assert "killed" in resp["error"]["message"].lower()
        release_slow.set()
        wait_stats(lambda s: s == (0, 0), "slow query never released")
        # admission is healthy afterwards: a fresh query runs through
        resp = post("after")
        while "nextUri" in resp:
            resp = _json.load(
                urllib.request.urlopen(resp["nextUri"], timeout=10)
            )
        assert resp["stats"]["state"] == "FINISHED", resp
        assert "victim" not in ran
    finally:
        release_slow.set()
        srv.stop()
