"""End-to-end TPC-H suite: engine results vs the sqlite oracle
(AbstractTestQueries + H2QueryRunner strategy, SURVEY.md §4.3)."""

import datetime
import re
import sqlite3

import pytest

from tests.oracle import assert_rows_match, load_tpch_sqlite, sqlite_rows
from tests.tpch_queries import QUERIES

SF = 0.01
_EPOCH = datetime.date(1970, 1, 1)


def _days(s: str) -> int:
    y, m, d = map(int, s.split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


def _shift(days: int, unit: str, n: int) -> int:
    d = _EPOCH + datetime.timedelta(days=days)
    if unit == "day":
        return days + n
    months = d.month - 1 + n * (12 if unit == "year" else 1)
    y = d.year + months // 12
    m = months % 12 + 1
    import calendar

    day = min(d.day, calendar.monthrange(y, m)[1])
    return (datetime.date(y, m, day) - _EPOCH).days


def to_sqlite(sql: str) -> str:
    """Translate the TPC-H dialect to the oracle's (dates are epoch-day
    INTEGER columns in sqlite — see tests/oracle.py)."""

    def fold_interval(m):
        days = _days(m.group(1))
        sign = 1 if m.group(2) == "+" else -1
        return str(_shift(days, m.group(4), sign * int(m.group(3))))

    sql = re.sub(
        r"date\s+'([0-9-]+)'\s*([+-])\s*interval\s+'(\d+)'\s+(day|month|year)",
        fold_interval,
        sql,
    )
    sql = re.sub(r"date\s+'([0-9-]+)'", lambda m: str(_days(m.group(1))), sql)
    sql = re.sub(
        r"extract\s*\(\s*year\s+from\s+([a-z_0-9.]+)\s*\)",
        r"CAST(strftime('%Y', (\1) * 86400, 'unixepoch') AS INTEGER)",
        sql,
    )
    sql = sql.replace("substring(", "substr(")

    # fold decimal-literal arithmetic exactly: sqlite would compute
    # 0.06 + 0.01 = 0.06999... and lose the 0.07 boundary row
    def fold_dec(m):
        from decimal import Decimal

        a, op, b = Decimal(m.group(1)), m.group(2), Decimal(m.group(3))
        return str(a + b if op == "+" else a - b)

    sql = re.sub(r"(\d+\.\d+)\s*([+-])\s*(\d+\.\d+)", fold_dec, sql)
    return sql


@pytest.fixture(scope="module")
def oracle():
    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def runner(tpch_local):
    return tpch_local


ORDERED = {q for q in QUERIES if "order by" in QUERIES[q]}


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_query(qid, runner, oracle):
    sql = QUERIES[qid]
    res = runner.execute(sql)
    expected = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(
        res.rows, expected, ordered=(qid in ORDERED), abs_tol=1e-2
    )


def test_simple_expressions(runner):
    assert runner.execute("SELECT 1 + 2 * 3").only_value() == 7
    assert runner.execute("SELECT CASE WHEN 1 > 2 THEN 'a' ELSE 'b' END").only_value() == "b"
    assert runner.execute("SELECT CAST(1.5 AS bigint)").only_value() == 2


def test_show_and_explain(runner):
    tables = runner.execute("SHOW TABLES").rows
    assert ["lineitem"] in tables
    plan = runner.execute("EXPLAIN SELECT count(*) FROM orders").only_value()
    assert "Scan" in plan and "Aggregate" in plan


def test_limit_offset(runner, oracle):
    res = runner.execute(
        "SELECT o_orderkey FROM orders ORDER BY o_orderkey LIMIT 5 OFFSET 3"
    )
    expected = sqlite_rows(
        oracle, "SELECT o_orderkey FROM orders ORDER BY o_orderkey LIMIT 5 OFFSET 3"
    )
    assert_rows_match(res.rows, expected, ordered=True)


def test_union(runner, oracle):
    # string unions with differing dictionaries raise NotImplementedError
    # at plan time (local_planner) — dictionary unification is planned work
    res = runner.execute(
        "SELECT o_custkey FROM orders WHERE o_custkey < 10"
        " UNION ALL SELECT c_custkey FROM customer WHERE c_custkey < 5"
    )
    expected = sqlite_rows(
        oracle,
        "SELECT o_custkey FROM orders WHERE o_custkey < 10"
        " UNION ALL SELECT c_custkey FROM customer WHERE c_custkey < 5",
    )
    assert_rows_match(res.rows, expected, ordered=False)

    res2 = runner.execute(
        "SELECT o_custkey FROM orders WHERE o_custkey < 10"
        " UNION SELECT c_custkey FROM customer WHERE c_custkey < 5"
    )
    expected2 = sqlite_rows(
        oracle,
        "SELECT o_custkey FROM orders WHERE o_custkey < 10"
        " UNION SELECT c_custkey FROM customer WHERE c_custkey < 5",
    )
    assert_rows_match(res2.rows, expected2, ordered=False)
