"""Polymorphic table functions: FROM TABLE(fn(...)) syntax, built-in
sequence/exclude_columns, and the connector TableFunction SPI
(spi/ptf/ConnectorTableFunction analogue)."""

import pytest

from trino_tpu import types as T
from trino_tpu.connectors.spi import ColumnMetadata, TableFunction
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    conn = create_tpch_connector()
    # a connector-provided ptf: multiplication table
    def times_table(args):
        n = int(args.get("n", args.get("_0", 3)))
        cols = [
            ColumnMetadata("a", T.BIGINT),
            ColumnMetadata("b", T.BIGINT),
            ColumnMetadata("product", T.BIGINT),
        ]
        rows = [
            (i, j, i * j)
            for i in range(1, n + 1)
            for j in range(1, n + 1)
        ]
        return cols, rows

    conn.table_functions["times_table"] = TableFunction(
        "times_table", times_table, "n x n multiplication table"
    )
    r.register_catalog("tpch", conn)
    return r


def test_sequence_positional(runner):
    rows = runner.execute(
        "select * from TABLE(sequence(1, 5))"
    ).rows
    assert rows == [[1], [2], [3], [4], [5]]


def test_sequence_named_args_and_step(runner):
    rows = runner.execute(
        "select * from TABLE(sequence(start => 0, stop => 10, step => 5))"
    ).rows
    assert rows == [[0], [5], [10]]


def test_sequence_column_name(runner):
    rows = runner.execute(
        "select sequential_number + 1 from TABLE(sequence(1, 3))"
    ).rows
    assert rows == [[2], [3], [4]]


def test_sequence_aliased(runner):
    rows = runner.execute(
        "select t.n from TABLE(sequence(2, 4)) as t(n) where t.n <> 3"
    ).rows
    assert rows == [[2], [4]]


def test_sequence_joins_with_tables(runner):
    rows = runner.execute(
        "select count(*) from TABLE(sequence(1, 3)) s, region r"
    ).rows
    assert rows == [[15]]


def test_exclude_columns(runner):
    rows = runner.execute(
        "select * from TABLE(exclude_columns("
        " input => TABLE(region), columns => DESCRIPTOR(r_comment)))"
        " order by r_regionkey limit 1"
    ).rows
    assert rows == [[0, "AFRICA"]]


def test_exclude_columns_unknown_column_errors(runner):
    with pytest.raises(Exception, match="no such columns"):
        runner.execute(
            "select * from TABLE(exclude_columns("
            " input => TABLE(region), columns => DESCRIPTOR(nope)))"
        )


def test_connector_table_function(runner):
    rows = runner.execute(
        "select product from TABLE(times_table(n => 4))"
        " where a = 4 and b = 4"
    ).rows
    assert rows == [[16]]


def test_unknown_table_function_errors(runner):
    with pytest.raises(Exception, match="unknown table function"):
        runner.execute("select * from TABLE(no_such_fn(1))")


def test_formatter_roundtrip():
    from trino_tpu.sql.formatter import format_statement
    from trino_tpu.sql.parser import parse

    for sql in [
        "SELECT * FROM TABLE(sequence(1, 5))",
        "SELECT * FROM TABLE(sequence(start => 1, stop => 5)) AS t(n)",
        "SELECT * FROM TABLE(exclude_columns(input => TABLE(r),"
        " columns => DESCRIPTOR(a, b)))",
    ]:
        tree = parse(sql)
        assert parse(format_statement(tree)) == tree
