"""TPC-DS connector + reporting-query family vs the sqlite oracle
(plugin/trino-tpcds analogue, SURVEY.md §2.12)."""

import sqlite3

import pytest

from tests.oracle import assert_rows_match, load_tpcds_sqlite, sqlite_rows
from trino_tpu.connectors.tpcds import create_tpcds_connector, row_count
from trino_tpu.engine import LocalQueryRunner, Session

SF = 0.01


@pytest.fixture(scope="module")
def oracle():
    conn = sqlite3.connect(":memory:")
    load_tpcds_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="tpcds", schema="tiny"))
    r.register_catalog("tpcds", create_tpcds_connector())
    return r


def test_row_counts(runner):
    assert runner.execute("SELECT count(*) FROM store_sales").only_value() == row_count("store_sales", SF)
    assert runner.execute("SELECT count(*) FROM date_dim").only_value() == row_count("date_dim", SF)
    assert runner.execute("SELECT count(*) FROM item").only_value() == row_count("item", SF)


# The classic star-join reporting family (q3/q42/q52/q55 shapes), with
# predicates that select real rows at tiny scale.
QUERIES = [
    # q3 shape: brand revenue by year for one category in one month
    """
    select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) sum_agg
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_category = 'Books' and d_moy = 11
    group by d_year, i_brand_id, i_brand
    order by d_year, sum_agg desc, i_brand_id
    limit 10
    """,
    # q42 shape: category revenue in one year/month
    """
    select d_year, i_category_id, i_category, sum(ss_ext_sales_price) s
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and d_moy = 12 and d_year = 2000
    group by d_year, i_category_id, i_category
    order by s desc, d_year, i_category_id, i_category
    limit 10
    """,
    # q52 shape: brand revenue one year/month
    """
    select d_year, i_brand_id brand_id, i_brand brand, sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and d_moy = 11 and d_year = 1999
    group by d_year, i_brand, i_brand_id
    order by d_year, ext_price desc, brand_id
    limit 10
    """,
    # q55 shape
    """
    select i_brand_id brand_id, i_brand brand, sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_category = 'Music' and d_moy = 12 and d_year = 2001
    group by i_brand, i_brand_id
    order by ext_price desc, brand_id
    limit 10
    """,
    # store-dimension join + state rollup
    """
    select s_state, count(*) c, sum(ss_net_profit) p
    from store_sales, store
    where ss_store_sk = s_store_sk
    group by s_state
    order by s_state
    """,
    # customer dimension join
    """
    select c_birth_year, count(*) c
    from store_sales, customer
    where ss_customer_sk = c_customer_sk and c_birth_year < 1940
    group by c_birth_year
    order by c_birth_year
    """,
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_tpcds_query(qi, runner, oracle):
    sql = QUERIES[qi]
    got = runner.execute(sql).rows
    want = sqlite_rows(oracle, sql)
    assert want, "oracle returned no rows — predicate selects nothing"
    assert_rows_match(got, want, ordered=True, abs_tol=1e-2)


def test_tpcds_distributed(oracle):
    from trino_tpu.runtime import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="tpcds", schema="tiny"), n_workers=2, hash_partitions=2
    )
    r.register_catalog("tpcds", create_tpcds_connector())
    sql = QUERIES[4]
    got = r.execute(sql).rows
    want = sqlite_rows(oracle, sql)
    assert_rows_match(got, want, ordered=True, abs_tol=1e-2)
