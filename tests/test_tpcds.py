"""TPC-DS connector + REAL query texts vs the sqlite oracle
(plugin/trino-tpcds analogue, SURVEY.md §2.12; VERDICT r1 item #8).

The queries below are the official TPC-DS templates q3/q7/q42/q43/q52/
q55/q65/q72/q82/q96 with parameter substitutions chosen to select rows
at tiny scale (parameter substitution is how the spec instantiates
templates). q72 is BASELINE config 4's deep multi-build join tree."""

import sqlite3

import pytest

from tests.oracle import assert_rows_match, load_tpcds_sqlite, sqlite_rows
from trino_tpu.connectors.tpcds import row_count

SF = 0.01


@pytest.fixture(scope="module")
def oracle():
    conn = sqlite3.connect(":memory:")
    load_tpcds_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def runner(tpcds_local):
    return tpcds_local


def test_row_counts(runner):
    assert runner.execute("SELECT count(*) FROM store_sales").only_value() == row_count("store_sales", SF)
    assert runner.execute("SELECT count(*) FROM inventory").only_value() == row_count("inventory", SF)
    assert runner.execute("SELECT count(*) FROM catalog_sales").only_value() == row_count("catalog_sales", SF)


QUERIES = {
    "q3": """
    select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
           sum(ss_ext_sales_price) sum_agg
    from date_dim dt, store_sales, item
    where dt.d_date_sk = store_sales.ss_sold_date_sk
      and store_sales.ss_item_sk = item.i_item_sk
      and item.i_manufact_id = 436
      and dt.d_moy = 12
    group by dt.d_year, item.i_brand, item.i_brand_id
    order by dt.d_year, sum_agg desc, brand_id
    limit 100
    """,
    "q7": """
    select i_item_id,
           avg(ss_quantity) agg1, avg(ss_list_price) agg2,
           avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
    from store_sales, customer_demographics, date_dim, item, promotion
    where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
      and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
      and cd_gender = 'M' and cd_marital_status = 'S'
      and cd_education_status = 'College'
      and (p_channel_email = 'N' or p_channel_event = 'N')
      and d_year = 2000
    group by i_item_id
    order by i_item_id
    limit 100
    """,
    "q42": """
    select dt.d_year, item.i_category_id, item.i_category,
           sum(ss_ext_sales_price)
    from date_dim dt, store_sales, item
    where dt.d_date_sk = store_sales.ss_sold_date_sk
      and store_sales.ss_item_sk = item.i_item_sk
      and item.i_manager_id = 1
      and dt.d_moy = 11 and dt.d_year = 2000
    group by dt.d_year, item.i_category_id, item.i_category
    order by sum(ss_ext_sales_price) desc, dt.d_year,
             item.i_category_id, item.i_category
    limit 100
    """,
    "q43": """
    select s_store_name, s_store_id,
      sum(case when (d_day_name = 'Sunday') then ss_sales_price else null end) sun_sales,
      sum(case when (d_day_name = 'Monday') then ss_sales_price else null end) mon_sales,
      sum(case when (d_day_name = 'Tuesday') then ss_sales_price else null end) tue_sales,
      sum(case when (d_day_name = 'Wednesday') then ss_sales_price else null end) wed_sales,
      sum(case when (d_day_name = 'Thursday') then ss_sales_price else null end) thu_sales,
      sum(case when (d_day_name = 'Friday') then ss_sales_price else null end) fri_sales,
      sum(case when (d_day_name = 'Saturday') then ss_sales_price else null end) sat_sales
    from date_dim, store_sales, store
    where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
      and s_gmt_offset = -5 and d_year = 2000
    group by s_store_name, s_store_id
    order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
             wed_sales, thu_sales, fri_sales, sat_sales
    limit 100
    """,
    "q52": """
    select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
           sum(ss_ext_sales_price) ext_price
    from date_dim dt, store_sales, item
    where dt.d_date_sk = store_sales.ss_sold_date_sk
      and store_sales.ss_item_sk = item.i_item_sk
      and item.i_manager_id = 1
      and dt.d_moy = 11 and dt.d_year = 2000
    group by dt.d_year, item.i_brand, item.i_brand_id
    order by dt.d_year, ext_price desc, brand_id
    limit 100
    """,
    "q55": """
    select i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) ext_price
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manager_id = 28 and d_moy = 11 and d_year = 1999
    group by i_brand, i_brand_id
    order by ext_price desc, brand_id
    limit 100
    """,
    "q65": """
    select s_store_name, i_item_desc, sc.revenue, i_current_price
    from store, item,
         (select ss_store_sk, avg(revenue) as ave
          from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
                from store_sales, date_dim
                where ss_sold_date_sk = d_date_sk
                  and d_month_seq between 1176 and 1176 + 11
                group by ss_store_sk, ss_item_sk) sa
          group by ss_store_sk) sb,
         (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
          from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk
            and d_month_seq between 1176 and 1176 + 11
          group by ss_store_sk, ss_item_sk) sc
    where sb.ss_store_sk = sc.ss_store_sk
      and sc.revenue <= 0.1 * sb.ave
      and s_store_sk = sc.ss_store_sk
      and i_item_sk = sc.ss_item_sk
    order by s_store_name, i_item_desc, sc.revenue
    limit 100
    """,
    "q72": """
    select i_item_desc, w_warehouse_name, d1.d_week_seq,
      sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
      sum(case when p_promo_sk is not null then 1 else 0 end) promo,
      count(*) total_cnt
    from catalog_sales
    join inventory on (cs_item_sk = inv_item_sk)
    join warehouse on (w_warehouse_sk = inv_warehouse_sk)
    join item on (i_item_sk = cs_item_sk)
    join customer_demographics on (cs_bill_cdemo_sk = cd_demo_sk)
    join household_demographics on (cs_bill_hdemo_sk = hd_demo_sk)
    join date_dim d1 on (cs_sold_date_sk = d1.d_date_sk)
    join date_dim d2 on (inv_date_sk = d2.d_date_sk)
    join date_dim d3 on (cs_ship_date_sk = d3.d_date_sk)
    left outer join promotion on (cs_promo_sk = p_promo_sk)
    left outer join catalog_returns on (cr_item_sk = cs_item_sk
                                        and cr_order_number = cs_order_number)
    where d1.d_week_seq = d2.d_week_seq
      and inv_quantity_on_hand < cs_quantity
      and d3.d_date > d1.d_date + 5
      and hd_buy_potential = '>10000'
      and d1.d_year = 1999
      and cd_marital_status = 'D'
    group by i_item_desc, w_warehouse_name, d1.d_week_seq
    order by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq
    limit 100
    """,
    # ^ spec text says bare `d_week_seq`, which the standard resolves to
    # the OUTPUT column; sqlite (the oracle) instead reports ambiguity
    # against d1/d2/d3, so the template qualifies it — same plan shape
    "q82": """
    select i_item_id, i_item_desc, i_current_price
    from item, inventory, date_dim, store_sales
    where i_current_price between 30 and 30 + 30
      and inv_item_sk = i_item_sk
      and d_date_sk = inv_date_sk
      and d_date between date '2002-05-30' and date '2002-07-29'
      and i_manufact_id in (437, 129, 727, 663)
      and inv_quantity_on_hand between 100 and 500
      and ss_item_sk = i_item_sk
    group by i_item_id, i_item_desc, i_current_price
    order by i_item_id
    limit 100
    """,
    "q96": """
    select count(*)
    from store_sales, household_demographics, time_dim, store
    where ss_sold_time_sk = time_dim.t_time_sk
      and ss_hdemo_sk = household_demographics.hd_demo_sk
      and ss_store_sk = s_store_sk
      and time_dim.t_hour = 20
      and time_dim.t_minute >= 30
      and household_demographics.hd_dep_count = 7
      and store.s_store_name = 'ese'
    """,
}

# queries that must select rows at tiny scale for the test to mean
# anything; parameters below are re-substituted from live data
_NONEMPTY = {"q3", "q7", "q42", "q43", "q52", "q55", "q72", "q82"}


def _sql_for(name, oracle):
    """Parameter substitution against the generated data (the spec
    instantiates templates the same way)."""
    sql = QUERIES[name]
    if name == "q96":
        (store_name,) = oracle.execute(
            "select s_store_name from store limit 1"
        ).fetchone()
        sql = sql.replace("'ese'", f"'{store_name}'")
    if name in ("q42", "q52"):
        (mgr,) = oracle.execute(
            "select i_manager_id from item group by i_manager_id"
            " order by count(*) desc limit 1"
        ).fetchone()
        sql = sql.replace("i_manager_id = 1", f"i_manager_id = {mgr}")
    if name == "q3":
        (mfg,) = oracle.execute(
            "select i_manufact_id from item group by i_manufact_id"
            " order by count(*) desc limit 1"
        ).fetchone()
        sql = sql.replace("i_manufact_id = 436", f"i_manufact_id = {mfg}")
    if name == "q82":
        ids = [
            str(r[0])
            for r in oracle.execute(
                "select distinct i_manufact_id from item"
                " where i_current_price between 30 and 60 limit 4"
            )
        ]
        sql = sql.replace("437, 129, 727, 663", ", ".join(ids) or "437")
    return sql


def _oracle_rows(oracle, sql):
    from tests.test_tpch import to_sqlite

    return sqlite_rows(oracle, to_sqlite(sql))


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpcds_query(name, runner, oracle):
    sql = _sql_for(name, oracle)
    got = runner.execute(sql).rows
    want = _oracle_rows(oracle, sql)
    if name in _NONEMPTY:
        assert want, f"{name}: oracle selected no rows at tiny scale"
    assert_rows_match(got, want, ordered=("order by" in sql), abs_tol=1e-2)


@pytest.mark.parametrize(
    "name",
    [
        "q3",
        # q72 distributed compiles ~6 min of XLA programs on a cold CPU
        # cache and was the single largest tier-1 wall-clock item (the
        # full suite overran its budget even before PR 5); it keeps
        # single-node oracle coverage above and distributed coverage in
        # the slow tier + bench.py
        pytest.param("q72", marks=pytest.mark.slow),
    ],
)
def test_tpcds_distributed(name, oracle, tpcds_cluster):
    r = tpcds_cluster
    sql = _sql_for(name, oracle)
    got = r.execute(sql).rows
    want = _oracle_rows(oracle, sql)
    assert_rows_match(got, want, ordered=("order by" in sql), abs_tol=1e-2)
