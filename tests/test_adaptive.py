"""Adaptive execution tier (PR 13, trino_tpu/adaptive/): mid-query
re-planning from observed stats + shared-subtree materialization.

The estimate->observe->re-plan loop runs at materialization barriers
(completed build sides): observed row counts are diffed against the
optimizer's estimates, and when the divergence crosses
adaptive_replan_threshold the REMAINING plan is re-optimized with the
completed subtree riding along as a literal source (never redone).
These tests force misestimates through a lying get_table_statistics
wrapper, then assert: re-plans trigger, results stay oracle-equal
across 0/1/2 re-plans, re-planned programs land on already-compiled
shapes, a deadline kill mid-re-plan stays typed, NOT IN's duplicated
subquery materializes once, and the off-path is untouched.
"""

import dataclasses

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.adaptive import SPOOL, AdaptiveController
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_TIME_LIMIT,
    ExceededTimeLimitError,
)


def _connector(seed=7, n=4000, n_keys=40):
    conn = MemoryConnector()
    rng = np.random.default_rng(seed)
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("k2", T.BIGINT),
         ColumnMetadata("v", T.BIGINT)],
        [rng.integers(0, n_keys, n).astype(np.int64),
         rng.integers(0, n_keys, n).astype(np.int64),
         rng.integers(0, 100, n).astype(np.int64)],
    )
    for name in ("dim1", "dim2"):
        conn.load_table(
            "s", name,
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("name", T.VARCHAR)],
            [np.arange(n_keys, dtype=np.int64),
             np.array([f"{name}-{i}" for i in range(n_keys)], dtype=object)],
        )
    return conn


def _lie_about_rows(conn, factors):
    """Scale get_table_statistics row counts per table name — the
    forced-misestimate fixture. factors: {table: multiplier}."""
    real = conn.metadata.get_table_statistics

    def lying(handle):
        ts = real(handle)
        f = factors.get(handle.table)
        if f is not None and ts.row_count is not None:
            return dataclasses.replace(ts, row_count=ts.row_count * f)
        return ts

    conn.metadata.get_table_statistics = lying


def _runner(conn, **session_kw):
    r = LocalQueryRunner(Session(catalog="memory", schema="s", **session_kw))
    r.register_catalog("memory", conn)
    return r


TWO_JOIN_Q = (
    "select d1.name, d2.name, sum(f.v) from facts f "
    "join dim1 d1 on f.k1 = d1.k join dim2 d2 on f.k2 = d2.k "
    "group by d1.name, d2.name order by 1, 2 limit 10"
)


def test_replan_triggers_on_misestimate():
    SPOOL.clear()
    conn = _connector()
    _lie_about_rows(conn, {"dim1": 0.1})
    r = _runner(conn, adaptive_execution=True, adaptive_replan_threshold=2.0)
    q = ("select d1.name, sum(f.v) from facts f join dim1 d1 "
         "on f.k1 = d1.k group by d1.name order by 1 limit 5")
    before = METRICS.snapshot().get("adaptive.replans", 0.0)
    rows = r.execute(q).rows
    report = r._last_adaptive_report
    assert report is not None and report.replans == 1
    obs = report.observations[0]
    assert obs["ratio"] >= 2.0 and obs.get("replanned")
    assert METRICS.snapshot().get("adaptive.replans", 0.0) - before >= 1
    # oracle: same connector, adaptive off (the lie does not change data)
    off = _runner(conn).execute(q).rows
    assert rows == off


@pytest.mark.parametrize(
    "factors,expected_replans",
    [
        ({}, 0),                           # estimates hold: observe only
        ({"dim2": 0.1}, 1),                # innermost build side lies
        ({"dim1": 0.1, "dim2": 0.1}, 2),   # both lie: budget of 2 spent
    ],
)
def test_oracle_equality_across_replans(factors, expected_replans):
    # dims sized so the optimizer keeps TWO join barriers (tiny dims
    # collapse into one cross-joined build side = a single barrier)
    SPOOL.clear()
    conn = _connector(n_keys=200)
    _lie_about_rows(conn, factors)
    r = _runner(conn, adaptive_execution=True, adaptive_replan_threshold=2.0)
    rows = r.execute(TWO_JOIN_Q).rows
    report = r._last_adaptive_report
    assert report is not None
    assert report.replans == expected_replans, report.as_dict()
    off = _runner(conn).execute(TWO_JOIN_Q).rows
    assert rows == off


def test_replanned_programs_mint_no_new_lowerings():
    """The zero-new-lowerings gate: a re-planned program must land on
    capacity-ladder shapes the first execution already compiled — the
    second adaptive run (same re-plan, warm spool) compiles nothing."""
    SPOOL.clear()
    conn = _connector()
    _lie_about_rows(conn, {"dim1": 0.1})
    r = _runner(conn, adaptive_execution=True, adaptive_replan_threshold=2.0)
    q = ("select d1.name, sum(f.v) from facts f join dim1 d1 "
         "on f.k1 = d1.k group by d1.name order by 1 limit 5")
    first = r.execute(q).rows
    assert r._last_adaptive_report.replans == 1
    before = METRICS.counter("xla_compiles")
    assert r.execute(q).rows == first
    delta = METRICS.counter("xla_compiles") - before
    assert delta == 0, f"adaptive re-run minted {delta} new lowerings"


def test_deadline_kill_mid_replan_stays_typed():
    """The controller's preempt hook fires at every barrier; a deadline
    kill landing there must surface as the TYPED deadline error, not a
    swallowed observation or an untyped crash."""
    SPOOL.clear()
    conn = _connector()
    _lie_about_rows(conn, {"dim1": 0.1})
    r = _runner(conn, adaptive_execution=True, adaptive_replan_threshold=2.0)
    from trino_tpu.sql.parser import parse

    root = r._analyze(parse(TWO_JOIN_Q))
    calls = [0]

    def preempt():
        calls[0] += 1
        if calls[0] >= 2:  # first barrier observed; kill mid-loop
            raise ExceededTimeLimitError(
                f"query exceeded planning limit [{EXCEEDED_TIME_LIMIT}]"
            )

    controller = AdaptiveController(r.catalogs, r.session, preempt=preempt)
    with pytest.raises(ExceededTimeLimitError) as ei:
        controller.prepare(root)
    assert EXCEEDED_TIME_LIMIT in str(ei.value)
    assert calls[0] >= 2
    # the kill must not have corrupted the spool: the same query still
    # runs to the oracle answer afterwards
    assert r.execute(TWO_JOIN_Q).rows == _runner(conn).execute(TWO_JOIN_Q).rows


def test_distributed_deadline_during_adaptive_planning_stays_typed():
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.runtime.coordinator import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny", retry_policy="task",
            adaptive_execution=True, adaptive_replan_threshold=1.3,
            query_max_planning_time_s=1e-6,
        ),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    with pytest.raises(ExceededTimeLimitError) as ei:
        r.execute(
            "select count(*) from supplier s join nation n "
            "on s_nationkey = n_nationkey where n_nationkey % 2 = 0"
        )
    assert EXCEEDED_TIME_LIMIT in str(ei.value)


def test_not_in_subquery_materializes_once():
    """NOT IN's rewrite plans the subquery twice; shared-subtree
    materialization runs it ONCE and feeds both seats from one
    generation-guarded spool entry."""
    SPOOL.clear()
    conn = _connector()
    r = _runner(conn, shared_subtree_materialization=True)
    q = ("select count(*) from facts where k1 not in "
         "(select k from dim1 where k < 10)")
    h0 = METRICS.snapshot().get("adaptive.spool_hits", 0.0)
    rows = r.execute(q).rows
    report = r._last_adaptive_report
    assert report is not None
    assert report.shared_subtrees == 1, report.as_dict()
    assert report.spool_stores == 1  # ran once ...
    assert report.spool_hits == 1    # ... second seat fed from the spool
    assert METRICS.snapshot().get("adaptive.spool_hits", 0.0) - h0 >= 1
    assert rows == _runner(conn).execute(q).rows


def test_spool_invalidated_by_table_write():
    """The spool is generation-guarded: DML on a source table drops the
    entry, so a re-run materializes fresh rows (oracle-equal, never
    stale)."""
    SPOOL.clear()
    conn = _connector()
    r = _runner(conn, shared_subtree_materialization=True)
    q = ("select count(*) from facts where k1 not in "
         "(select k from dim1 where k < 100)")
    first = r.execute(q).rows
    r.execute("insert into dim1 values (50, 'late')")
    # k1 < 40 in facts, so adding key 50 changes nothing semantically —
    # but the generation bump must force a fresh materialization
    second = r.execute(q).rows
    assert second == _runner(conn).execute(q).rows
    assert first == second  # key 50 never matches any fact row


def test_divergence_recorded_with_adaptive_off():
    """adaptive_execution=off still reports: distributed EXPLAIN
    ANALYZE carries per-fragment estimated_vs_observed lines and the
    divergence counter moves — but no re-plan and no plan transform."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.runtime.coordinator import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", adaptive_replan_threshold=1.3),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    q = ("select count(*) from supplier s join nation n "
         "on s_nationkey = n_nationkey where n_nationkey % 2 = 0")
    d0 = METRICS.snapshot().get("adaptive.divergences", 0.0)
    r0 = METRICS.snapshot().get("adaptive.replans", 0.0)
    txt = r.execute("explain analyze " + q).rows[0][0]
    assert "estimated_vs_observed: fragment:" in txt
    assert "SpooledValues" not in txt
    assert "adaptive:" not in txt  # no controller section when off
    assert METRICS.snapshot().get("adaptive.divergences", 0.0) > d0
    assert METRICS.snapshot().get("adaptive.replans", 0.0) == r0


def test_off_path_plans_byte_identical():
    """With every adaptive property at its default-off value, EXPLAIN
    output is byte-identical to a plain session's — the tier leaves the
    off-path untouched."""
    conn = _connector()
    plain = _runner(conn).execute("explain " + TWO_JOIN_Q).rows[0][0]
    off = _runner(
        conn, adaptive_execution=False, shared_subtree_materialization=False
    ).execute("explain " + TWO_JOIN_Q).rows[0][0]
    assert plain == off
    assert "SpooledValues" not in plain


def test_analyze_renders_adaptive_section_locally():
    SPOOL.clear()
    conn = _connector()
    _lie_about_rows(conn, {"dim1": 0.1})
    r = _runner(conn, adaptive_execution=True, adaptive_replan_threshold=2.0)
    q = ("select d1.name, sum(f.v) from facts f join dim1 d1 "
         "on f.k1 = d1.k group by d1.name order by 1 limit 5")
    txt = r.execute("explain analyze " + q).rows[0][0]
    assert "adaptive: observations=" in txt, txt
    assert "estimated_vs_observed: build:" in txt
    assert "-> replanned" in txt
