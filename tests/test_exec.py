"""Exec-layer tests: hand-built operator pipelines vs the sqlite oracle
(the tier-2 LocalQueryRunner strategy, SURVEY.md §4.2, before the SQL
frontend exists)."""

import sqlite3

import pytest

from tests.oracle import assert_rows_match, epoch_days, load_tpch_sqlite, sqlite_rows
from trino_tpu import types as T
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.exec import (
    AggSpec,
    CollectorSink,
    CrossJoinBuildSink,
    CrossJoinOperator,
    Driver,
    FilterProjectOperator,
    HashAggregationOperator,
    HashBuildSink,
    JoinBridge,
    LimitOperator,
    LookupJoinOperator,
    Pipeline,
    SortOperator,
    TableScanOperator,
    TopNOperator,
)
from trino_tpu.expr.compile import ExprBinder
from trino_tpu.expr.ir import Call, InputRef, Literal
from trino_tpu.ops.sort import SortKey

SF = 0.01


@pytest.fixture(scope="module")
def oracle():
    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def tpch():
    return create_tpch_connector()


def scan(tpch, table, columns, batch_rows=65536, schema="tiny"):
    handle = tpch.metadata.get_table_handle(schema, table)
    splits = tpch.split_manager.get_splits(handle, 1)
    op = TableScanOperator(tpch.page_source, splits, columns, batch_rows)
    meta = tpch.metadata.get_table_metadata(handle)
    types = [meta.columns[meta.column_index(c)].type for c in columns]
    dicts = [tpch.metadata.column_dictionary(handle, c) for c in columns]
    return op, types, dicts


def run(ops):
    sink = CollectorSink()
    Driver(Pipeline(ops + [sink])).run()
    return sink.rows()


def test_scan_filter_project(oracle, tpch):
    op, types, dicts = scan(tpch, "lineitem", ["l_orderkey", "l_quantity"])
    b = ExprBinder(types, dicts)
    flt = b.bind(
        Call("lt", (InputRef(1, types[1]), Literal(25, T.decimal(12, 2))), T.BOOLEAN)
    )
    proj = [b.bind(InputRef(0, types[0]))]
    rows = run([op, FilterProjectOperator(flt, proj)])
    expected = sqlite_rows(
        oracle, "SELECT l_orderkey FROM lineitem WHERE l_quantity < 25"
    )
    assert_rows_match(rows, expected, ordered=False)


def test_hash_aggregation(oracle, tpch):
    cols = ["l_returnflag", "l_linestatus", "l_quantity"]
    op, types, dicts = scan(tpch, "lineitem", cols)
    agg = HashAggregationOperator(
        [0, 1],
        [
            AggSpec("sum", 2, T.decimal(18, 2)),
            AggSpec("count_star", None, T.BIGINT),
            AggSpec("avg", 2, T.DOUBLE),
            AggSpec("min", 2, T.decimal(12, 2)),
            AggSpec("max", 2, T.decimal(12, 2)),
        ],
        list(zip(types, dicts)),
        initial_capacity=16,  # force growth paths
    )
    rows = run([op, agg])
    expected = sqlite_rows(
        oracle,
        "SELECT l_returnflag, l_linestatus, ROUND(SUM(l_quantity), 2), COUNT(*),"
        " AVG(l_quantity), MIN(l_quantity), MAX(l_quantity)"
        " FROM lineitem GROUP BY 1, 2",
    )
    assert_rows_match(rows, expected, ordered=False)


def test_hash_aggregation_growth(oracle, tpch):
    """High-cardinality keys from a tiny initial table: exercises the
    grow_table rebuild + accumulator migration paths (incl. min/max
    extreme re-init and multi-doubling in one batch)."""
    cols = ["l_partkey", "l_quantity"]
    op, types, dicts = scan(tpch, "lineitem", cols, batch_rows=4096)
    agg = HashAggregationOperator(
        [0],
        [
            AggSpec("sum", 1, T.decimal(18, 2)),
            AggSpec("min", 1, T.decimal(12, 2)),
            AggSpec("max", 1, T.decimal(12, 2)),
            AggSpec("count_star", None, T.BIGINT),
        ],
        list(zip(types, dicts)),
        initial_capacity=16,
    )
    rows = run([op, agg])
    expected = sqlite_rows(
        oracle,
        "SELECT l_partkey, ROUND(SUM(l_quantity), 2), MIN(l_quantity),"
        " MAX(l_quantity), COUNT(*) FROM lineitem GROUP BY 1",
    )
    assert_rows_match(rows, expected, ordered=False)


def test_global_aggregation_empty_input(oracle, tpch):
    op, types, dicts = scan(tpch, "lineitem", ["l_quantity"])
    b = ExprBinder(types, dicts)
    flt = b.bind(
        Call("lt", (InputRef(0, types[0]), Literal(-1, T.decimal(12, 2))), T.BOOLEAN)
    )
    agg = HashAggregationOperator(
        [],
        [AggSpec("sum", 0, T.decimal(18, 2)), AggSpec("count_star", None, T.BIGINT)],
        list(zip(types, dicts)),
    )
    rows = run([op, FilterProjectOperator(flt, [b.bind(InputRef(0, types[0]))]), agg])
    assert rows == [[None, 0]]


def test_inner_join(oracle, tpch):
    bridge = JoinBridge()
    bop, btypes, bdicts = scan(tpch, "customer", ["c_custkey", "c_mktsegment"])
    build_sink = HashBuildSink(bridge, [0], list(zip(btypes, bdicts)))
    Driver(Pipeline([bop, build_sink])).run()

    pop, ptypes, pdicts = scan(tpch, "orders", ["o_custkey", "o_totalprice"])
    join = LookupJoinOperator(bridge, [0], "inner", list(zip(ptypes, pdicts)))
    rows = run([pop, join])
    expected = sqlite_rows(
        oracle,
        "SELECT o_custkey, o_totalprice, c_custkey, c_mktsegment"
        " FROM orders JOIN customer ON o_custkey = c_custkey",
    )
    assert_rows_match(rows, expected, ordered=False)


def test_semi_anti_join(oracle, tpch):
    for jt, sql in [
        (
            "semi",
            "SELECT c_custkey FROM customer WHERE EXISTS"
            " (SELECT 1 FROM orders WHERE o_custkey = c_custkey)",
        ),
        (
            "anti",
            "SELECT c_custkey FROM customer WHERE NOT EXISTS"
            " (SELECT 1 FROM orders WHERE o_custkey = c_custkey)",
        ),
    ]:
        bridge = JoinBridge()
        bop, btypes, bdicts = scan(tpch, "orders", ["o_custkey"])
        Driver(
            Pipeline([bop, HashBuildSink(bridge, [0], list(zip(btypes, bdicts)))])
        ).run()
        pop, ptypes, pdicts = scan(tpch, "customer", ["c_custkey"])
        join = LookupJoinOperator(bridge, [0], jt, list(zip(ptypes, pdicts)))
        rows = run([pop, join])
        assert_rows_match(rows, sqlite_rows(oracle, sql), ordered=False)


def test_left_join(oracle, tpch):
    bridge = JoinBridge()
    bop, btypes, bdicts = scan(tpch, "orders", ["o_custkey", "o_totalprice"])
    Driver(
        Pipeline([bop, HashBuildSink(bridge, [0], list(zip(btypes, bdicts)))])
    ).run()
    pop, ptypes, pdicts = scan(tpch, "customer", ["c_custkey"])
    join = LookupJoinOperator(bridge, [0], "left", list(zip(ptypes, pdicts)))
    rows = run([pop, join])
    expected = sqlite_rows(
        oracle,
        "SELECT c_custkey, o_custkey, o_totalprice FROM customer"
        " LEFT JOIN orders ON o_custkey = c_custkey",
    )
    assert_rows_match(rows, expected, ordered=False)


def test_join_residual_filter(oracle, tpch):
    """Residual non-equi condition applied inside the join (Q21 pattern)."""
    bridge = JoinBridge()
    bop, btypes, bdicts = scan(tpch, "lineitem", ["l_orderkey", "l_suppkey"])
    Driver(
        Pipeline([bop, HashBuildSink(bridge, [0], list(zip(btypes, bdicts)))])
    ).run()
    pop, ptypes, pdicts = scan(tpch, "lineitem", ["l_orderkey", "l_suppkey"])
    pair_types = ptypes + btypes
    pair_dicts = pdicts + bdicts
    rb = ExprBinder(pair_types, pair_dicts)
    residual = rb.bind(
        Call("ne", (InputRef(1, ptypes[1]), InputRef(3, btypes[1])), T.BOOLEAN)
    )
    join = LookupJoinOperator(
        bridge, [0], "semi", list(zip(ptypes, pdicts)), residual=residual
    )
    rows = run([pop, join])
    expected = sqlite_rows(
        oracle,
        "SELECT l1.l_orderkey, l1.l_suppkey FROM lineitem l1 WHERE EXISTS"
        " (SELECT 1 FROM lineitem l2 WHERE l2.l_orderkey = l1.l_orderkey"
        "  AND l2.l_suppkey <> l1.l_suppkey)",
    )
    assert_rows_match(rows, expected, ordered=False)


def test_cross_join_scalar(oracle, tpch):
    # scalar subquery: orders with o_totalprice > (SELECT AVG(o_totalprice)...)
    sop, stypes, sdicts = scan(tpch, "orders", ["o_totalprice"])
    agg = HashAggregationOperator(
        [], [AggSpec("avg", 0, T.DOUBLE)], list(zip(stypes, sdicts))
    )
    bridge = JoinBridge()
    Driver(
        Pipeline([sop, agg, CrossJoinBuildSink(bridge, [(T.DOUBLE, None)])])
    ).run()
    pop, ptypes, pdicts = scan(tpch, "orders", ["o_orderkey", "o_totalprice"])
    cross = CrossJoinOperator(bridge)
    b = ExprBinder(ptypes + [T.DOUBLE], pdicts + [None])
    flt = b.bind(Call("gt", (InputRef(1, ptypes[1]), InputRef(2, T.DOUBLE)), T.BOOLEAN))
    rows = run([pop, cross, FilterProjectOperator(flt, [b.bind(InputRef(0, ptypes[0]))])])
    expected = sqlite_rows(
        oracle,
        "SELECT o_orderkey FROM orders WHERE o_totalprice >"
        " (SELECT AVG(o_totalprice) FROM orders)",
    )
    assert_rows_match(rows, expected, ordered=False)


def test_topn_and_sort(oracle, tpch):
    op, types, dicts = scan(tpch, "orders", ["o_orderkey", "o_totalprice"])
    topn = TopNOperator(
        [SortKey(1, descending=True), SortKey(0)], 10, list(zip(types, dicts))
    )
    rows = run([op, topn])
    expected = sqlite_rows(
        oracle,
        "SELECT o_orderkey, o_totalprice FROM orders"
        " ORDER BY o_totalprice DESC, o_orderkey LIMIT 10",
    )
    assert_rows_match(rows, expected, ordered=True)

    op2, types2, dicts2 = scan(tpch, "customer", ["c_custkey", "c_mktsegment"])
    sort = SortOperator([SortKey(1), SortKey(0, descending=True)], list(zip(types2, dicts2)))
    rows2 = run([op2, sort])
    expected2 = sqlite_rows(
        oracle,
        "SELECT c_custkey, c_mktsegment FROM customer"
        " ORDER BY c_mktsegment, c_custkey DESC",
    )
    assert_rows_match(rows2, expected2, ordered=True)


def test_limit(tpch):
    op, types, dicts = scan(tpch, "orders", ["o_orderkey"], batch_rows=1000)
    rows = run([op, LimitOperator(2500)])
    assert len(rows) == 2500


def test_q1_pipeline(oracle, tpch):
    """Hand-built TPC-H Q1 — the minimum end-to-end slice of SURVEY §7.4
    at the operator level (the SQL frontend repeats this from text)."""
    cols = [
        "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
        "l_discount", "l_tax", "l_shipdate",
    ]
    op, types, dicts = scan(tpch, "lineitem", cols)
    b = ExprBinder(types, dicts)
    dec = T.decimal(12, 2)
    one = Literal(1, T.decimal(12, 2))
    flt = b.bind(
        Call("le", (InputRef(6, T.DATE), Literal(epoch_days("1998-09-02"), T.DATE)), T.BOOLEAN)
    )
    disc_price = Call(
        "mul",
        (
            InputRef(3, dec),
            Call("sub", (one, InputRef(4, dec)), T.decimal(12, 2)),
        ),
        T.decimal(18, 4),
    )
    charge = Call(
        "mul",
        (disc_price, Call("add", (one, InputRef(5, dec)), T.decimal(12, 2))),
        T.decimal(18, 6),
    )
    projections = [
        b.bind(InputRef(0, types[0])),
        b.bind(InputRef(1, types[1])),
        b.bind(InputRef(2, dec)),
        b.bind(InputRef(3, dec)),
        b.bind(disc_price),
        b.bind(charge),
        b.bind(InputRef(4, dec)),
    ]
    proj_schema = [(p.type, p.dictionary) for p in projections]
    agg = HashAggregationOperator(
        [0, 1],
        [
            AggSpec("sum", 2, T.decimal(18, 2)),
            AggSpec("sum", 3, T.decimal(18, 2)),
            AggSpec("sum", 4, T.decimal(18, 4)),
            AggSpec("sum", 5, T.decimal(18, 6)),
            AggSpec("avg", 2, T.DOUBLE),
            AggSpec("avg", 3, T.DOUBLE),
            AggSpec("avg", 6, T.DOUBLE),
            AggSpec("count_star", None, T.BIGINT),
        ],
        proj_schema,
    )
    agg_schema = [(types[0], dicts[0]), (types[1], dicts[1])] + [
        (T.decimal(18, 2), None), (T.decimal(18, 2), None), (T.decimal(18, 4), None),
        (T.decimal(18, 6), None), (T.DOUBLE, None), (T.DOUBLE, None),
        (T.DOUBLE, None), (T.BIGINT, None),
    ]
    sort = SortOperator([SortKey(0), SortKey(1)], agg_schema)
    rows = run([op, FilterProjectOperator(flt, projections), agg, sort])
    expected = sqlite_rows(
        oracle,
        f"""
        SELECT l_returnflag, l_linestatus,
               ROUND(SUM(l_quantity), 2), ROUND(SUM(l_extendedprice), 2),
               ROUND(SUM(l_extendedprice * (1 - l_discount)), 4),
               ROUND(SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)), 6),
               AVG(l_quantity), AVG(l_extendedprice), AVG(l_discount), COUNT(*)
        FROM lineitem WHERE l_shipdate <= {epoch_days('1998-09-02')}
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
        """,
    )
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-4)
