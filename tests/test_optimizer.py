"""Optimizer: memo, iterative rules, cost-based join reordering
(sql/optimizer.py — IterativeOptimizer/Memo/ReorderJoins analogues).

Rule tests build small plan-IR trees directly; the reorder tests verify
both the plan-shape change (cheap build side chosen, cross joins
eliminated) and result correctness through the engine (the whole
TPC-H oracle suite also runs with the optimizer on, in test_tpch.py).
"""

import pytest

from trino_tpu import types as T
from trino_tpu.expr import ir
from trino_tpu.sql import plan as P
from trino_tpu.sql.cost import CostCalculator
from trino_tpu.sql.optimizer import (
    IterativeOptimizer,
    Memo,
    ReorderJoins,
    optimize,
)
from trino_tpu.sql.stats import StatsCalculator


def f(*names):
    return tuple(P.Field(n, T.BIGINT) for n in names)


def values(n_rows, *names):
    return P.ValuesNode(f(*names), tuple((i,) * len(names) for i in range(n_rows)))


def ref(i):
    return ir.InputRef(i, T.BIGINT)


def lit(v):
    return ir.Literal(v, T.BIGINT)


def test_memo_roundtrip():
    scan = values(3, "a")
    tree = P.FilterNode(
        P.ProjectNode(scan, (ref(0),), f("a")),
        ir.comparison("gt", ref(0), lit(1)),
        f("a"),
    )
    memo = Memo(tree)
    assert memo.extract() == tree


def test_merge_filters():
    scan = values(5, "a")
    tree = P.FilterNode(
        P.FilterNode(scan, ir.comparison("gt", ref(0), lit(1)), scan.fields),
        ir.comparison("lt", ref(0), lit(4)),
        scan.fields,
    )
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.FilterNode)
    assert isinstance(out.child, P.ValuesNode)
    assert isinstance(out.predicate, ir.Call) and out.predicate.name == "and"


def test_remove_identity_project():
    scan = values(2, "a", "b")
    tree = P.ProjectNode(scan, (ref(0), ref(1)), scan.fields)
    out = IterativeOptimizer().optimize(tree)
    assert out == scan


def test_inline_projections():
    scan = values(2, "a")
    inner = P.ProjectNode(
        scan, (ir.call("add", T.BIGINT, ref(0), lit(1)),), f("x")
    )
    outer = P.ProjectNode(
        inner, (ir.call("mul", T.BIGINT, ref(0), lit(2)),), f("y")
    )
    out = IterativeOptimizer().optimize(outer)
    assert isinstance(out, P.ProjectNode)
    assert isinstance(out.child, P.ValuesNode)
    # mul(add(a, 1), 2)
    e = out.exprs[0]
    assert e.name == "mul" and e.args[0].name == "add"


def test_limit_over_sort_to_topn():
    from trino_tpu.ops.sort import SortKey

    scan = values(9, "a")
    tree = P.LimitNode(
        P.SortNode(scan, (SortKey(0),), scan.fields), 3, 0, scan.fields
    )
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.TopNNode) and out.count == 3


def test_push_filter_into_join():
    left = values(4, "a")
    right = values(4, "b")
    join = P.JoinNode("inner", left, right, (0,), (0,), None, f("a", "b"))
    tree = P.FilterNode(
        join,
        ir.and_(
            ir.comparison("gt", ref(0), lit(0)),   # left side only
            ir.comparison("lt", ref(1), lit(3)),   # right side only
        ),
        join.fields,
    )
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.JoinNode)
    assert isinstance(out.left, P.FilterNode)
    assert isinstance(out.right, P.FilterNode)
    # equality inference mirrors each single-channel conjunct across
    # the a = b join key, so BOTH sides carry both bounds, re-based to
    # each child's channels
    def _conjs(pred):
        return sorted(
            (c.name, c.args[0].index, c.args[1].value)
            for c in (
                pred.args if pred.name == "and" else (pred,)
            )
        )

    assert _conjs(out.left.predicate) == [("gt", 0, 0), ("lt", 0, 3)]
    assert _conjs(out.right.predicate) == [("gt", 0, 0), ("lt", 0, 3)]


class _FakeCatalogs:
    def get(self, name):
        raise KeyError(name)


def _reorderer():
    stats = StatsCalculator(_FakeCatalogs())
    return ReorderJoins(stats, CostCalculator(stats))


def test_reorder_puts_small_side_on_build():
    big = values(1000, "a")
    small = values(2, "b")
    # analyzer-style: big joins small, but with SMALL as probe side
    join = P.JoinNode("inner", small, big, (0,), (0,), None, f("b", "a"))
    out = _reorderer().rewrite(join)
    # reorderer flips: big probes, small builds; a Project restores order
    assert isinstance(out, P.ProjectNode)
    j = out.child
    assert isinstance(j, P.JoinNode)
    assert len(j.left.rows) == 1000 and len(j.right.rows) == 2


def test_reorder_three_way_chain():
    a = values(1000, "a")
    b = values(500, "b")
    c = values(2, "c")
    # chain a-b, b-c assembled badly: (a JOIN b) then c as probe
    ab = P.JoinNode("inner", a, b, (0,), (0,), None, f("a", "b"))
    abc = P.JoinNode("inner", c, ab, (0,), (1,), None, f("c", "a", "b"))
    out = _reorderer().rewrite(abc)
    # schema must be preserved exactly
    assert out.fields == abc.fields

    def count_joins(n):
        k = 1 if isinstance(n, P.JoinNode) else 0
        return k + sum(count_joins(ch) for ch in n.children())

    assert count_joins(out) == 2


def test_reorder_eliminates_cross_join():
    a = values(100, "a")
    b = values(100, "b")
    c = values(100, "c")
    # (a CROSS b) JOIN c with edges a-c and b-c: reordering should find
    # an edge-connected order with no cross join at all
    ab = P.JoinNode("cross", a, b, (), (), None, f("a", "b"))
    abc = P.JoinNode(
        "inner", ab, c, (0, 1), (0, 0), None, f("a", "b", "c")
    )
    out = _reorderer().rewrite(abc)

    def has_cross(n):
        if isinstance(n, P.JoinNode) and n.kind == "cross":
            return True
        return any(has_cross(ch) for ch in n.children())

    # the cross-joined pair is a region LEAF boundary (cross joins bound
    # the clean-inner region), so at minimum the plan stays correct
    assert out.fields == abc.fields


def test_reorder_region_spans_inner_tree():
    # 4 relations, star: fact joins three small dims; assembled as a
    # left-deep chain probing fact last
    fact = values(1000, "f")
    d1, d2, d3 = values(3, "x"), values(4, "y"), values(5, "z")
    t = P.JoinNode("inner", d1, fact, (0,), (0,), None, f("x", "f"))
    t = P.JoinNode("inner", t, d2, (0,), (0,), None, f("x", "f", "y"))
    t = P.JoinNode("inner", t, d3, (1,), (0,), None, f("x", "f", "y", "z"))
    out = _reorderer().rewrite(t)
    assert out.fields == t.fields
    # fact must end up as a probe side (left), never a build side
    def no_fact_build(n):
        if isinstance(n, P.JoinNode):
            if isinstance(n.right, P.ValuesNode) and len(n.right.rows) == 1000:
                return False
            return all(no_fact_build(ch) for ch in n.children())
        return all(no_fact_build(ch) for ch in n.children())

    assert no_fact_build(out)


# -- end-to-end: results stay correct with reordering on and off --


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import LocalQueryRunner, Session

    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


Q3ISH = """
select o_orderkey, sum(l_extendedprice) rev
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey
group by o_orderkey order by rev desc limit 5
"""


def test_reordering_preserves_results(runner):
    on = runner.execute(Q3ISH).rows
    runner.execute("SET SESSION join_reordering_strategy = none")
    try:
        off = runner.execute(Q3ISH).rows
    finally:
        runner.execute("SET SESSION join_reordering_strategy = automatic")
    assert on == off and len(on) == 5


def test_optimizer_off_preserves_results(runner):
    on = runner.execute(Q3ISH).rows
    runner.execute("SET SESSION enable_optimizer = false")
    try:
        off = runner.execute(Q3ISH).rows
    finally:
        runner.execute("SET SESSION enable_optimizer = true")
    assert on == off


# -- r4 rule-breadth additions (VERDICT item: optimizer rule breadth) --


def test_merge_limits():
    scan = values(20, "a")
    tree = P.LimitNode(
        P.LimitNode(scan, 10, 2, scan.fields), 4, 1, scan.fields
    )
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.LimitNode)
    assert not isinstance(out.child, P.LimitNode)
    # child window [2, 12); outer skips 1, takes 4 -> rows [3, 7)
    assert out.offset == 3 and out.count == 4


def test_push_limit_through_project():
    scan = values(9, "a")
    proj = P.ProjectNode(scan, (ref(0),), f("b"))
    tree = P.LimitNode(proj, 3, 0, proj.fields)
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.ProjectNode)
    assert isinstance(out.child, P.LimitNode) and out.child.count == 3


def test_push_topn_through_project_direct_key():
    from trino_tpu.ops.sort import SortKey

    scan = values(9, "a", "b")
    proj = P.ProjectNode(scan, (ref(1), ref(0)), f("x", "y"))
    tree = P.TopNNode(proj, (SortKey(0),), 3, proj.fields)
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.ProjectNode)
    assert isinstance(out.child, P.TopNNode)
    assert out.child.keys[0].channel == 1  # remapped through the proj


def test_push_topn_not_through_computed_key():
    from trino_tpu.ops.sort import SortKey

    scan = values(9, "a")
    proj = P.ProjectNode(
        scan, (ir.call("add", T.BIGINT, ref(0), lit(1)),), f("x")
    )
    tree = P.TopNNode(proj, (SortKey(0),), 3, proj.fields)
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.TopNNode)  # computed key: no push


def test_remove_trivial_filters():
    scan = values(5, "a")
    t = P.FilterNode(scan, ir.Literal(True, T.BOOLEAN), scan.fields)
    out = IterativeOptimizer().optimize(t)
    assert isinstance(out, P.ValuesNode) and len(out.rows) == 5
    t2 = P.FilterNode(scan, ir.Literal(False, T.BOOLEAN), scan.fields)
    out2 = IterativeOptimizer().optimize(t2)
    assert isinstance(out2, P.ValuesNode) and not out2.rows


def test_push_limit_through_union():
    a, b = values(8, "a"), values(8, "a")
    u = P.UnionAllNode((a, b), a.fields)
    tree = P.LimitNode(u, 3, 1, a.fields)
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.LimitNode)
    assert out.count == 3 and out.offset == 1
    union = out.child
    assert isinstance(union, P.UnionAllNode)
    for inp in union.inputs:
        assert isinstance(inp, P.LimitNode) and inp.count == 4
