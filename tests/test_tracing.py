"""End-to-end query tracing, stats aggregation, and the observability
plane (PR 7): span-tree invariants across success/failure/kill/retry/
chaos runs, Chrome trace-event export schema, distribution metrics,
per-query compile-counter retention, enriched completion events, and
the aggregated QueryInfo REST surface.
"""

import json
import urllib.error
import urllib.request

import pytest

from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime import DistributedQueryRunner, Worker
from trino_tpu.runtime.chaos import ChaosHarness, rows_equal
from trino_tpu.runtime.failure import FailureInjector
from trino_tpu.runtime.metrics import (
    METRICS,
    Distribution,
    retire_query_compiles,
)
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_TIME_LIMIT,
    ExceededTimeLimitError,
)
from trino_tpu.runtime.tracing import (
    KIND_OPERATOR,
    KIND_PHASE,
    KIND_QUERY,
    KIND_STAGE,
    KIND_TASK,
    QueryTrace,
    check_span_invariants,
    chrome_trace,
    wire_context,
)

SEED = 42

Q_AGG = (
    "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)
Q_JOIN = (
    "select n_name, count(*) c from supplier, nation "
    "where s_nationkey = n_nationkey "
    "group by n_name order by n_name"
)


def _cluster(n_workers=2, **session_kw):
    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [
        Worker(f"tr-w{i}", cats, failure_injector=inj)
        for i in range(n_workers)
    ]
    runner = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", **session_kw),
        worker_handles=workers, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())
    return inj, runner


# -- tracer unit tests ------------------------------------------------------


def test_wire_context_and_remote_graft():
    """The coordinator hands a task span's context across the wire; the
    worker records operator spans against it; graft closes the tree and
    dedups repeat deliveries (a task polled twice)."""
    trace = QueryTrace("q1")
    root = trace.span("query q1", KIND_QUERY)
    stage = root.child("stage 0", KIND_STAGE)
    task = stage.child("task q1.0.0.0", KIND_TASK)
    ctx = wire_context(task)
    assert set(ctx) == {"trace_id", "span_id"}

    remote = QueryTrace.remote(ctx)
    op = remote.span("ScanOperator", KIND_OPERATOR, parent=ctx["span_id"])
    op.set(input_rows=25)
    op.end()
    shipped = remote.export()["spans"]
    assert trace.graft(shipped) == 1
    assert trace.graft(shipped) == 0  # dedup by span_id
    task.end()
    stage.end()
    root.end()
    export = trace.export()
    assert check_span_invariants(export) == []
    grafted = [s for s in export["spans"] if s["kind"] == "operator"]
    assert grafted[0]["parent_id"] == task.span_id
    assert grafted[0]["trace_id"] == trace.trace_id  # rewritten on graft


def test_end_open_spans_sweeps_abnormal_completion():
    trace = QueryTrace("q2")
    root = trace.span("query q2", KIND_QUERY)
    root.child("stage 0", KIND_STAGE)  # never ended
    assert "unclosed" in " ".join(check_span_invariants(trace.export()))
    assert trace.end_open_spans() == 2
    assert check_span_invariants(trace.export()) == []


def test_span_context_manager_annotates_exceptions():
    trace = QueryTrace("q3")
    root = trace.span("query q3", KIND_QUERY)
    with pytest.raises(ValueError):
        with root.child("analyze", KIND_PHASE) as s:
            raise ValueError("boom")
    assert s.ended
    assert s.attributes.get("error") is True
    assert s.events[0]["name"] == "exception"


def test_chrome_trace_schema():
    """Golden structural schema for the Perfetto export: thread-name
    metadata first, one complete ("X") event per span with microsecond
    ts/dur, instant ("i") events for annotations, and track assignment
    that gives stages and task attempts their own rows."""
    trace = QueryTrace("q4")
    root = trace.span("query q4", KIND_QUERY)
    ph = root.child("analyze", KIND_PHASE)
    ph.end()
    stage = root.child("stage 0", KIND_STAGE)
    task = stage.child("task t0", KIND_TASK)
    task.event("task_retry", attempt=1)
    op = task.child("ScanOperator", KIND_OPERATOR)
    op.end()
    task.end()
    stage.end()
    root.end()

    events = chrome_trace(trace.export())
    json.dumps(events)  # must be JSON-serializable as-is
    meta = [e for e in events if e["ph"] == "M"]
    complete = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert {e["ph"] for e in events} == {"M", "X", "i"}
    assert all(e["name"] == "thread_name" for e in meta)
    assert len(complete) == 5  # one per span
    for e in complete:
        assert set(e) == {"name", "cat", "ph", "ts", "dur", "pid",
                          "tid", "args"}
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert "span_id" in e["args"]
    assert instants and instants[0]["name"] == "task_retry"
    assert instants[0]["s"] == "t"
    by_name = {e["name"]: e["tid"] for e in complete}
    assert by_name["query q4"] == 0  # coordinator track
    assert by_name["analyze"] == 0  # phases ride the coordinator track
    assert by_name["stage 0"] not in (0, by_name["task t0"])
    assert by_name["ScanOperator"] == by_name["task t0"]  # ops inherit


# -- distribution metrics ---------------------------------------------------


def test_distribution_percentiles_and_summary():
    d = Distribution()
    for ms in range(1, 101):
        d.add(ms / 1000.0)
    s = d.summary()
    assert s["count"] == 100
    assert s["min"] == pytest.approx(0.001)
    assert s["max"] == pytest.approx(0.100)
    assert 0 < s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
    # bucket edges are powers of two: one-bucket (~2x) error bound
    assert s["p50"] == pytest.approx(0.05, rel=1.5)


def test_distribution_empty_is_zero():
    d = Distribution()
    assert d.percentile(0.99) == 0.0
    assert d.summary()["count"] == 0


def test_metrics_snapshot_flattens_distributions():
    name = "test_tracing_dist_s"
    try:
        METRICS.observe(name, 0.25)
        snap = METRICS.snapshot()
        for stat in ("count", "avg", "p50", "p95", "p99"):
            assert f"{name}.{stat}" in snap
    finally:
        METRICS.remove_prefix(name)


# -- per-query compile-counter retention ------------------------------------


def test_compile_counter_registry_stays_bounded():
    """1000 queries' worth of per-query compile counters retire into
    QueryInfo at completion; the registry must not grow with query
    count (the leak this PR fixes)."""
    base = len(METRICS.counter_names())
    for i in range(1000):
        qid = f"boundq{i}"
        METRICS.increment(f"xla_compiles_by_query.{qid}", 2)
        METRICS.increment(f"xla_compiles_by_query.{qid}r1")  # query retry
        assert retire_query_compiles(qid) == 3
    assert len(METRICS.counter_names()) == base
    assert not [
        n for n in METRICS.counter_names()
        if n.startswith("xla_compiles_by_query.boundq")
    ]


def test_compile_counter_retirement_is_prefix_safe():
    """Retiring q3 must not swallow q30 (exact id + `r` retry suffix
    only, never a bare prefix match)."""
    METRICS.increment("xla_compiles_by_query.prefq3", 1)
    METRICS.increment("xla_compiles_by_query.prefq3r1", 1)
    METRICS.increment("xla_compiles_by_query.prefq30", 5)
    try:
        assert retire_query_compiles("prefq3") == 2
        assert METRICS.counter("xla_compiles_by_query.prefq30") == 5
    finally:
        METRICS.remove_prefix("xla_compiles_by_query.prefq3")


# -- enriched completion events ---------------------------------------------


def test_jsonl_event_listener_writes_one_line_per_query(tmp_path):
    from trino_tpu.runtime.events import JsonlEventListener

    path = tmp_path / "queries.jsonl"
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    r.event_listeners.add(JsonlEventListener(str(path)))
    r.execute("select count(*) from region")
    r.execute("select count(*) from nation")
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) == 2
    first = lines[0]
    assert first["event"] == "query_completed"
    assert first["state"] == "finished"
    assert first["rows"] == 1
    for key in ("peak_memory_bytes", "rows_scanned", "bytes_scanned",
                "rows_shuffled", "compile_count", "retry_count",
                "attempt_count", "error_code", "emit_time"):
        assert key in first, key


def test_dispatch_failures_surfaces_as_gauge():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    assert "event_listener_dispatch_failures" in METRICS.snapshot()
    r.register_catalog("tpch", create_tpch_connector())

    class Broken:
        def query_created(self, e):
            raise RuntimeError("boom")

        def query_completed(self, e):
            pass

    r.event_listeners.add(Broken())
    r.execute("select 1")
    assert METRICS.snapshot()["event_listener_dispatch_failures"] >= 1


# -- distributed tracing end to end -----------------------------------------


@pytest.fixture(scope="module")
def traced():
    """One traced cluster shared by the happy-path assertions."""
    inj, runner = _cluster(query_trace="on")
    runner.execute(Q_AGG)
    return inj, runner, runner.last_query_id


def test_traced_query_exports_complete_span_tree(traced):
    _, runner, qid = traced
    export = runner.query_trace_export(qid)
    assert export is not None and export["query_id"] == qid
    assert check_span_invariants(export) == []
    kinds = {s["kind"] for s in export["spans"]}
    assert kinds == {"query", "phase", "stage", "task", "operator"}
    phases = {s["name"] for s in export["spans"] if s["kind"] == "phase"}
    assert {"parse", "analyze", "optimize", "fragment",
            "schedule"} <= phases
    # one task span per scheduled task, each under a stage span
    by_id = {s["span_id"]: s for s in export["spans"]}
    for s in export["spans"]:
        if s["kind"] == "task":
            assert by_id[s["parent_id"]]["kind"] == "stage"
        if s["kind"] == "operator":
            assert by_id[s["parent_id"]]["kind"] == "task"
    # operator spans carry their final stats as attributes
    ops = [s for s in export["spans"] if s["kind"] == "operator"]
    assert any(s["attributes"].get("input_rows", 0) > 0 for s in ops)


def test_traced_query_chrome_export_loads(traced):
    _, runner, qid = traced
    doc = runner.query_chrome_trace(qid)
    assert doc is not None
    events = doc["traceEvents"]
    json.dumps(doc)
    assert {"M", "X"} <= {e["ph"] for e in events}
    names = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert "coordinator" in names
    assert any(n.startswith("stage") for n in names)
    assert any(n.startswith("task") for n in names)


def test_query_info_aggregates_stage_and_operator_stats(traced):
    _, runner, qid = traced
    info = runner.query_info(qid)
    assert info["query_id"] == qid and info["state"] == "finished"
    assert info["wall_s"] > 0
    assert info["stages"], "no per-stage rollup"
    summaries = [
        op for st in info["stages"] for group in st["operator_summaries"]
        for op in group
    ]
    assert any(op["input_rows"] > 0 for op in summaries)
    leaf = info["stages"][-1]
    assert leaf["tasks"] >= 1 and len(leaf["task_infos"]) == leaf["tasks"]
    assert all(t["wall_s"] is not None for t in leaf["task_infos"])
    # census-vs-ledger lowering comparison rode the TaskInfo surface
    assert "expected_lowerings" in leaf and "observed_lowerings" in leaf


def test_wall_time_distributions_recorded(traced):
    snap = METRICS.snapshot()
    for name in ("query_wall_s", "stage_wall_s"):
        for stat in ("p50", "p95", "p99"):
            assert f"{name}.{stat}" in snap, f"{name}.{stat}"
    assert snap["query_wall_s.count"] >= 1


def test_query_endpoints_over_http(traced):
    from trino_tpu.runtime.server import CoordinatorServer

    _, runner, qid = traced
    srv = CoordinatorServer(runner, port=0)
    try:
        def get(path):
            return json.load(urllib.request.urlopen(
                srv.uri + path, timeout=10
            ))

        info = get(f"/v1/query/{qid}")
        assert info["query_id"] == qid and info["stages"]
        doc = get(f"/v1/query/{qid}/trace")
        assert any(e["ph"] == "X" for e in doc["traceEvents"])
        snap = get("/v1/metrics")
        assert "query_wall_s.p50" in snap
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                srv.uri + "/v1/query/no-such-query", timeout=10
            )
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_untraced_query_records_no_trace():
    """query_trace defaults off: no per-query trace is retained, but
    the QueryInfo rollup (coordinator-side stats) still lands."""
    _, runner = _cluster()
    runner.execute(Q_JOIN)
    qid = runner.last_query_id
    info = runner.query_info(qid)
    assert info is not None and info["state"] == "finished"
    export = runner.query_trace_export(qid)
    # coordinator spans exist either way; operator spans must NOT
    # (workers only record them when the wire context says so)
    assert not [s for s in export["spans"] if s["kind"] == "operator"]


def test_failed_query_still_closes_its_trace():
    _, runner = _cluster(query_trace="on")
    with pytest.raises(Exception):
        runner.execute("select no_such_column from region")
    qid = runner.last_query_id
    export = runner.query_trace_export(qid)
    assert check_span_invariants(export) == []
    root = export["spans"][0]
    assert root["attributes"]["state"] == "failed"
    assert any(e["name"] == "exception" for e in root["events"])
    assert runner.query_info(qid)["state"] == "failed"


def test_deadline_killed_query_trace_reads_as_one_timeline():
    inj, runner = _cluster(
        query_trace="on", query_max_execution_time_s=0.2,
    )
    inj.inject(where="batch", attempts=(0, 1, 2, 3), stall_s=20.0,
               max_hits=1)
    try:
        with pytest.raises(ExceededTimeLimitError):
            runner.execute(Q_AGG)
    finally:
        inj.clear()
    qid = runner.last_query_id
    export = runner.query_trace_export(qid)
    assert check_span_invariants(export) == []
    info = runner.query_info(qid)
    assert info["state"] == "failed"
    assert info["error_code"] == EXCEEDED_TIME_LIMIT
    # the enforcement sweep that fired the kill recorded its duration
    assert "tracker_tick_s.p50" in METRICS.snapshot()


def test_fte_retry_and_chaos_annotations_land_on_spans():
    """A crash-injected FTE run must read as one timeline: the failed
    attempt's task span carries a chaos_fault annotation, the stage
    span a task_retry, and the replayed attempt closes the tree."""
    inj, runner = _cluster(retry_policy="task", query_trace="on")
    inj.inject(where="start", kind="crash", fragment_id=0, partition=0,
               attempts=(0,), max_hits=1)
    try:
        rows = runner.execute(Q_JOIN).rows
    finally:
        inj.clear()
    assert rows
    qid = runner.last_query_id
    export = runner.query_trace_export(qid)
    assert check_span_invariants(export) == []
    task_events = [
        e["name"] for s in export["spans"] if s["kind"] == "task"
        for e in s["events"]
    ]
    stage_events = [
        e["name"] for s in export["spans"] if s["kind"] == "stage"
        for e in s["events"]
    ]
    assert "chaos_fault" in task_events
    assert "task_retry" in stage_events
    # the retry shows up as a second task-attempt span
    assert any(
        s["attributes"].get("attempt", 0) >= 1
        for s in export["spans"] if s["kind"] == "task"
    )


# -- operator-internal heartbeats / tightened watchdog ----------------------


def test_watchdog_fires_fast_on_warm_hung_operator():
    """Operator-internal heartbeats (every add_input/get_output entry
    and exit) let the WARM stuck-task threshold drop to hundreds of
    milliseconds — far below the old ~1s batch-granularity floor — and
    a wedged task is interrupted well before the injected stall."""
    h = ChaosHarness(
        n_workers=3,
        stuck_task_interrupt_s=2.0,
        stuck_task_interrupt_warm_s=0.3,
        memory_pool_bytes=256 << 20,
    )
    h.register_catalog("tpch", create_tpch_connector())
    rows, report = h.run_hung_operator_case(Q_AGG, seed=SEED, stall_s=8.0)
    assert rows_equal(rows, h.run_clean(Q_AGG), ordered=True)
    assert report["watchdog_interrupts"], "watchdog never fired"
    assert any(
        "Stuck task" in d for d in report["watchdog_interrupts"]
    )
    overhead = report["elapsed_s"] - report["warm_clean_s"]
    assert overhead < report["stall_s"] / 2, (
        f"tightened warm threshold did not unwedge quickly "
        f"(overhead {overhead:.2f}s vs stall {report['stall_s']}s)"
    )
