"""Plan-quality passes (PR 1): equality inference, connector pushdown,
partial-aggregation placement, redundant-exchange elimination — plus
the engine counters (rows_scanned / bytes_scanned / rows_shuffled /
exchanges_elided) that make the wins assertable.

Plan-shape tests build IR trees directly (test_optimizer.py idiom);
e2e tests assert counter DELTAS across runs with pushdown on vs off,
oracle-checked against sqlite so "fewer rows scanned" never trades
away correctness.
"""

import sqlite3

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.expr import ir
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.sql import plan as P
from trino_tpu.sql.optimizer import IterativeOptimizer


def f(*names):
    return tuple(P.Field(n, T.BIGINT) for n in names)


def values(n_rows, *names):
    return P.ValuesNode(
        f(*names), tuple((i,) * len(names) for i in range(n_rows))
    )


def ref(i):
    return ir.InputRef(i, T.BIGINT)


def lit(v):
    return ir.Literal(v, T.BIGINT)


def _scanned():
    return METRICS.snapshot().get("rows_scanned", 0.0)


def _bytes():
    return METRICS.snapshot().get("bytes_scanned", 0.0)


# -- EqualityInference: transitive predicates across join keys --


def test_transitive_predicate_derived_for_join_key():
    left = values(4, "a")
    right = values(4, "b")
    join = P.JoinNode("inner", left, right, (0,), (0,), None, f("a", "b"))
    tree = P.FilterNode(
        join, ir.comparison("eq", ref(0), lit(2)), join.fields
    )
    out = IterativeOptimizer().optimize(tree)
    # a = 2 over a = b must derive b = 2: both children filtered
    assert isinstance(out, P.JoinNode)
    assert isinstance(out.left, P.FilterNode)
    assert isinstance(out.right, P.FilterNode)
    r = out.right.predicate
    assert r.name == "eq" and r.args[0].index == 0 and r.args[1].value == 2


def test_transitive_inference_spans_conjunct_equalities():
    # filter carries BOTH the equality (a = b) and a bound on a:
    # the bound must transfer to b even without join-key equivalence
    scan = values(6, "a", "b")
    tree = P.FilterNode(
        scan,
        ir.and_(
            ir.comparison("eq", ref(0), ref(1)),
            ir.comparison("gt", ref(0), lit(3)),
        ),
        scan.fields,
    )
    out = IterativeOptimizer().optimize(tree)
    assert isinstance(out, P.FilterNode)
    txt = repr(out.predicate)
    # derived: gt($1, 3) alongside the originals
    assert "gt" in txt and "$[1" in txt


# -- PushPredicateIntoTableScan / PushProjectionIntoTableScan --


@pytest.fixture()
def mem_runner():
    r = LocalQueryRunner(Session(catalog="memory", schema="s"))
    r.register_catalog("memory", create_memory_connector())
    mem = r.catalogs.get("memory")
    rng = np.random.default_rng(3)
    n = 10_000
    mem.load_table(
        "s", "t",
        [
            ColumnMetadata("k", T.BIGINT),
            ColumnMetadata("v", T.BIGINT),
            ColumnMetadata("w", T.DOUBLE),
        ],
        [
            np.arange(n, dtype=np.int64),
            rng.integers(0, 100, n, dtype=np.int64),
            rng.random(n),
        ],
    )
    return r


def test_scan_carries_pushed_conjuncts(mem_runner):
    txt = mem_runner.execute(
        "explain select v from t where k < 100 and k >= 10"
    ).rows[0][0]
    assert "pushed=[" in txt
    assert "k lt 100" in txt and "k ge 10" in txt
    assert "Filter" not in txt  # fully absorbed: no residual


def test_unsupported_conjunct_stays_residual(mem_runner):
    # v + 1 < 10 is not `col op literal`: must remain a FilterNode
    txt = mem_runner.execute(
        "explain select v from t where k < 100 and v + 1 < 10"
    ).rows[0][0]
    assert "pushed=[k lt 100]" in txt
    assert "Filter" in txt and "add" in txt


def test_pushdown_results_match_and_scan_less(mem_runner):
    sql = "select sum(v) from t where k < 500"
    s0 = _scanned()
    on = mem_runner.execute(sql).rows
    s1 = _scanned()
    mem_runner.execute("SET SESSION enable_pushdown = false")
    try:
        off = mem_runner.execute(sql).rows
    finally:
        mem_runner.execute("SET SESSION enable_pushdown = true")
    s2 = _scanned()
    assert on == off
    assert s1 - s0 < s2 - s1  # strictly fewer live rows with pushdown


def test_count_star_scans_single_narrow_column(mem_runner):
    txt = mem_runner.execute("explain select count(*) from t").rows[0][0]
    assert "Scan memory.s.t ['k']" in txt


def test_projection_narrowed_to_used_columns(mem_runner):
    txt = mem_runner.execute("explain select v + 1 from t").rows[0][0]
    assert "'v'" in txt and "'w'" not in txt and "'k'" not in txt


# -- TPC-H Q6/Q3: counter-asserted, oracle-checked --


Q6 = """
select sum(l_extendedprice * l_discount) as revenue from lineitem
where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
  o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
"""


@pytest.fixture(scope="module")
def tpch_runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(scope="module")
def tpch_oracle():
    from tests.oracle import load_tpch_sqlite

    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, 0.01)
    yield conn
    conn.close()


@pytest.mark.parametrize("name,sql", [("q6", Q6), ("q3", Q3)])
def test_tpch_rows_scanned_drops_with_pushdown(
    name, sql, tpch_runner, tpch_oracle
):
    from tests.oracle import assert_rows_match, sqlite_rows
    from tests.test_tpch import to_sqlite

    s0 = _scanned()
    on = tpch_runner.execute(sql).rows
    s1 = _scanned()
    tpch_runner.execute("SET SESSION enable_pushdown = false")
    try:
        off = tpch_runner.execute(sql).rows
    finally:
        tpch_runner.execute("SET SESSION enable_pushdown = true")
    s2 = _scanned()
    assert on == off
    assert s1 - s0 < s2 - s1, (s1 - s0, s2 - s1)
    expected = sqlite_rows(tpch_oracle, to_sqlite(sql))
    assert_rows_match(
        on, expected, ordered=("order by" in sql), abs_tol=1e-2
    )


# -- parquet: row-group skipping lowers bytes_scanned --


def test_parquet_bytes_scanned_drops_with_pushdown(tmp_path):
    from trino_tpu.connectors.file import create_file_connector
    from trino_tpu.connectors.parquet_format import (
        ParquetColumn,
        T_INT64,
        write_parquet,
    )

    n = 4000
    (tmp_path / "s").mkdir()
    write_parquet(
        str(tmp_path / "s" / "t.parquet"),
        [
            ParquetColumn(
                "id", T_INT64, values=np.arange(n, dtype=np.int64)
            ),
            ParquetColumn(
                "v", T_INT64,
                values=np.arange(n, dtype=np.int64) * 3,
            ),
        ],
        n,
        row_group_rows=500,
    )
    r = LocalQueryRunner(Session(catalog="file", schema="s"))
    r.register_catalog("file", create_file_connector(str(tmp_path)))
    sql = "select sum(v) from t where id < 600"
    b0 = _bytes()
    on = r.execute(sql).rows
    b1 = _bytes()
    r.execute("SET SESSION enable_pushdown = false")
    off = r.execute(sql).rows
    b2 = _bytes()
    assert on == off == [[sum(i * 3 for i in range(600))]]
    # min/max row-group stats skip 6 of 8 groups
    assert b1 - b0 < b2 - b1, (b1 - b0, b2 - b1)


# -- fragmenter: partial-agg placement + redundant-exchange removal --


def _agg_over(child, group_channels, fields):
    return P.AggregateNode(
        child,
        group_channels,
        (P.AggCall("sum", 1, T.BIGINT),),
        fields,
        step="single",
    )


def test_push_partial_aggregation_through_exchange():
    from trino_tpu.sql.fragmenter import (
        push_partial_aggregation_through_exchange,
    )

    scan = values(8, "k", "v")
    ex = P.ExchangeNode(scan, "repartition", (0,), scan.fields)
    tree = _agg_over(ex, (0,), f("k", "s"))
    out = push_partial_aggregation_through_exchange(tree)
    # single agg over exchange -> final over exchange over partial
    assert isinstance(out, P.AggregateNode) and out.step == "final"
    assert isinstance(out.child, P.ExchangeNode)
    part = out.child.child
    assert isinstance(part, P.AggregateNode) and part.step == "partial"
    assert part.child is scan
    assert out.fields == tree.fields


def test_partial_agg_not_pushed_for_holistic():
    from trino_tpu.sql.fragmenter import (
        push_partial_aggregation_through_exchange,
    )

    scan = values(8, "k", "v")
    ex = P.ExchangeNode(scan, "repartition", (0,), scan.fields)
    tree = P.AggregateNode(
        ex, (0,),
        (P.AggCall("approx_distinct", 1, T.BIGINT),),
        f("k", "d"), step="single",
    )
    out = push_partial_aggregation_through_exchange(tree)
    assert out == tree  # holistic kinds must not split


def test_eliminate_back_to_back_repartitions():
    from trino_tpu.sql.fragmenter import eliminate_redundant_exchanges

    scan = values(8, "k", "v")
    inner = P.ExchangeNode(scan, "repartition", (0,), scan.fields)
    outer = P.ExchangeNode(inner, "repartition", (0,), scan.fields)
    out = eliminate_redundant_exchanges(outer)
    assert isinstance(out, P.ExchangeNode)
    assert out.child is scan  # inner exchange removed


def test_keeps_different_key_repartitions():
    from trino_tpu.sql.fragmenter import eliminate_redundant_exchanges

    scan = values(8, "k", "v")
    inner = P.ExchangeNode(scan, "repartition", (1,), scan.fields)
    outer = P.ExchangeNode(inner, "repartition", (0,), scan.fields)
    out = eliminate_redundant_exchanges(outer)
    assert isinstance(out.child, P.ExchangeNode)  # different keys: kept


def test_distributed_plan_partial_below_repartition():
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.sql.analyzer import Analyzer
    from trino_tpu.sql.fragmenter import plan_distributed
    from trino_tpu.sql.parser import parse

    c = CatalogManager()
    c.register("tpch", create_tpch_connector())
    analyzer = Analyzer(c, "tpch", "tiny")
    output = analyzer.plan(parse(
        "select l_returnflag, sum(l_quantity) from lineitem"
        " group by l_returnflag"
    ))
    sp = plan_distributed(output, c)
    steps = []

    def walk(n):
        if isinstance(n, P.AggregateNode):
            steps.append(n.step)
        for ch in n.children():
            walk(ch)

    for frag in sp.all_fragments():
        walk(frag.root)
    assert sorted(steps) == ["final", "partial"]


# -- co-bucketed join: exchanges_elided counter fires --


def test_cobucketed_join_elides_exchanges():
    from trino_tpu.runtime import DistributedQueryRunner

    rng = np.random.default_rng(11)
    ka = rng.integers(0, 500, 3000).astype(np.int64)
    va = rng.integers(0, 100, 3000).astype(np.int64)
    kb = rng.integers(0, 500, 2000).astype(np.int64)
    wb = rng.integers(0, 100, 2000).astype(np.int64)

    def make(bucketed):
        mem = create_memory_connector()
        bb = ("k",) if bucketed else None
        mem.load_table(
            "d", "ta",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
            [ka, va], bucketed_by=bb,
        )
        mem.load_table(
            "d", "tb",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
            [kb, wb], bucketed_by=bb,
        )
        s = Session(catalog="memory", schema="d", mesh_execution=False,
                    broadcast_join_threshold=0)
        r = DistributedQueryRunner(s, n_workers=2, hash_partitions=2)
        r.register_catalog("memory", mem)
        return r

    sql = (
        "select ta.k, sum(ta.v + tb.w) from ta join tb on ta.k = tb.k"
        " group by ta.k order by 1"
    )
    e0 = METRICS.snapshot().get("exchanges_elided", 0.0)
    sh0 = METRICS.snapshot().get("rows_shuffled", 0.0)
    bucketed_rows = make(True).execute(sql).rows
    e1 = METRICS.snapshot().get("exchanges_elided", 0.0)
    sh1 = METRICS.snapshot().get("rows_shuffled", 0.0)
    plain_rows = make(False).execute(sql).rows
    sh2 = METRICS.snapshot().get("rows_shuffled", 0.0)
    assert bucketed_rows == plain_rows
    assert e1 - e0 > 0  # join + agg over declared bucketing plan free
    # and the co-bucketed run moves fewer rows through exchanges
    assert sh1 - sh0 < sh2 - sh1


# -- multi-range pushdown (PR 13): IN-lists and OR-of-ranges ----------


def test_in_list_pushed_and_exact(mem_runner):
    txt = mem_runner.execute(
        "explain select v from t where k in (3, 1, 4, 1, 5)"
    ).rows[0][0]
    # canonical sorted/deduped tuple on the scan, no residual Filter
    assert "k in (1, 3, 4, 5)" in txt, txt
    assert "Filter" not in txt
    rows = mem_runner.execute(
        "select sum(v) from t where k in (3, 1, 4, 1, 5)"
    ).rows
    mem_runner.execute("SET SESSION enable_pushdown = false")
    try:
        off = mem_runner.execute(
            "select sum(v) from t where k in (3, 1, 4, 1, 5)"
        ).rows
    finally:
        mem_runner.execute("SET SESSION enable_pushdown = true")
    assert rows == off


def test_or_of_ranges_pushed_and_exact(mem_runner):
    sql = "select sum(v) from t where k < 5 or k > 9995"
    txt = mem_runner.execute("explain " + sql).rows[0][0]
    assert "k (lt 5 or gt 9995)" in txt, txt
    assert "Filter" not in txt
    s0 = _scanned()
    on = mem_runner.execute(sql).rows
    s1 = _scanned()
    mem_runner.execute("SET SESSION enable_pushdown = false")
    try:
        off = mem_runner.execute(sql).rows
    finally:
        mem_runner.execute("SET SESSION enable_pushdown = true")
    s2 = _scanned()
    assert on == off
    assert s1 - s0 < s2 - s1  # exact enforcement still prunes rows


def test_or_across_columns_stays_residual(mem_runner):
    # disjuncts on different columns can't become one ColumnConstraint
    txt = mem_runner.execute(
        "explain select v from t where k < 5 or v > 90"
    ).rows[0][0]
    assert "pushed=" not in txt
    assert "Filter" in txt


def test_in_list_with_null_option_stays_residual(mem_runner):
    txt = mem_runner.execute(
        "explain select v from t where k in (1, 2, null)"
    ).rows[0][0]
    assert "pushed=" not in txt


def test_parquet_row_groups_pruned_by_in_list(tmp_path):
    from trino_tpu.connectors.file import create_file_connector
    from trino_tpu.connectors.parquet_format import (
        ParquetColumn,
        T_INT64,
        write_parquet,
    )

    n = 4000
    (tmp_path / "s").mkdir()
    write_parquet(
        str(tmp_path / "s" / "t.parquet"),
        [
            ParquetColumn("id", T_INT64, values=np.arange(n, dtype=np.int64)),
            ParquetColumn(
                "v", T_INT64, values=np.arange(n, dtype=np.int64) * 3
            ),
        ],
        n,
        row_group_rows=500,
    )
    r = LocalQueryRunner(Session(catalog="file", schema="s"))
    r.register_catalog("file", create_file_connector(str(tmp_path)))
    # IN bounds to [100, 120]: one of 8 groups survives min/max pruning
    sql = "select sum(v) from t where id in (100, 110, 120)"
    b0 = _bytes()
    on = r.execute(sql).rows
    b1 = _bytes()
    r.execute("SET SESSION enable_pushdown = false")
    off = r.execute(sql).rows
    b2 = _bytes()
    assert on == off == [[(100 + 110 + 120) * 3]]
    assert b1 - b0 < b2 - b1, (b1 - b0, b2 - b1)


def test_dynamic_filter_domain_lands_on_probe_scan():
    """PR 13: the dynamic-filter bridge's build-side key domain is
    re-used as a runtime scan constraint — the probe TableScanOperator
    merges an IN-list (small domains) into its splits before producing,
    so connector-level enforcement prunes rows the DynamicFilterOperator
    would otherwise drop one batch later."""
    r = LocalQueryRunner(Session(catalog="memory", schema="s"))
    r.register_catalog("memory", create_memory_connector())
    mem = r.catalogs.get("memory")
    rng = np.random.default_rng(5)
    n = 5000
    mem.load_table(
        "s", "big",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [rng.integers(0, 1000, n).astype(np.int64),
         rng.integers(0, 100, n).astype(np.int64)],
    )
    mem.load_table(
        "s", "small",
        [ColumnMetadata("k", T.BIGINT)],
        [np.array([5, 10, 17], dtype=np.int64)],
    )
    sql = "select count(*), sum(b.v) from big b join small s on b.k = s.k"
    c0 = METRICS.snapshot().get("dynamic_filter_scan_constraints", 0.0)
    s0 = _scanned()
    on = r.execute(sql).rows
    c1 = METRICS.snapshot().get("dynamic_filter_scan_constraints", 0.0)
    s1 = _scanned()
    assert c1 - c0 >= 1  # the probe scan took the bridge's domain
    r.execute("SET SESSION enable_dynamic_filtering = false")
    off = r.execute(sql).rows
    s2 = _scanned()
    assert on == off
    # the constrained scan produced only matching rows
    assert s1 - s0 < s2 - s1, (s1 - s0, s2 - s1)
