"""Parquet file format: pure-python reader/writer + file-connector
integration (lib/trino-parquet reduced to the engine's types —
VERDICT r2 missing #5 / next #8)."""

import glob
import os

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.file import create_file_connector
from trino_tpu.connectors.parquet_format import (
    C_DATE,
    C_DECIMAL,
    C_UTF8,
    ParquetColumn,
    T_BOOLEAN,
    T_BYTE_ARRAY,
    T_DOUBLE,
    T_INT32,
    T_INT64,
    read_parquet,
    write_parquet,
)
from trino_tpu.engine import LocalQueryRunner, Session


def _sample_columns(n=10):
    return [
        ParquetColumn("id", T_INT64, values=np.arange(n, dtype=np.int64)),
        ParquetColumn(
            "price", T_INT64, C_DECIMAL, scale=2, precision=12,
            values=np.arange(n, dtype=np.int64) * 100 + 5,
        ),
        ParquetColumn(
            "d", T_INT32, C_DATE,
            values=np.arange(n, dtype=np.int32) + 9000,
        ),
        ParquetColumn(
            "x", T_DOUBLE, values=np.linspace(0, 1, n),
            valid=np.asarray([i % 3 != 0 for i in range(n)]),
        ),
        ParquetColumn(
            "name", T_BYTE_ARRAY, C_UTF8,
            values=[f"s{i}".encode() for i in range(n)],
            valid=np.asarray([i != 5 for i in range(n)]),
        ),
        ParquetColumn(
            "flag", T_BOOLEAN,
            values=np.asarray([i % 2 == 0 for i in range(n)]),
        ),
    ]


def test_format_roundtrip(tmp_path):
    p = str(tmp_path / "t.parquet")
    cols = _sample_columns()
    write_parquet(p, cols, 10)
    back, n = read_parquet(p)
    assert n == 10
    for c0, c1 in zip(cols, back):
        assert (c0.name, c0.physical, c0.converted, c0.scale) == (
            c1.name, c1.physical, c1.converted, c1.scale
        )
    assert back[0].values.tolist() == list(range(10))
    assert back[3].valid.tolist() == [i % 3 != 0 for i in range(10)]
    assert back[4].values[0] == b"s0" and not back[4].valid[5]
    assert back[5].values.tolist() == [i % 2 == 0 for i in range(10)]


def test_file_connector_reads_parquet(tmp_path):
    os.makedirs(tmp_path / "s")
    write_parquet(str(tmp_path / "s" / "orders.parquet"),
                  _sample_columns(), 10)
    r = LocalQueryRunner(Session(catalog="file", schema="s"))
    r.register_catalog("file", create_file_connector(str(tmp_path)))
    cols = dict(r.execute("show columns from orders").rows)
    assert cols["price"] == "decimal(12,2)"
    assert cols["d"] == "date"
    assert cols["name"] == "varchar"
    res = r.execute(
        "select count(*), count(x), sum(price), min(name) from orders"
    )
    assert res.rows == [[10, 6, 45.5, "s0"]]
    # date semantics survive (epoch-days storage)
    assert r.execute(
        "select id from orders where d = date '1994-08-26'"
    ).rows == [[3]]


def test_parquet_ctas_write_and_readback(tmp_path):
    os.makedirs(tmp_path / "src")
    write_parquet(str(tmp_path / "src" / "t.parquet"),
                  _sample_columns(), 10)
    out_root = str(tmp_path / "out_root")
    r = LocalQueryRunner(Session(catalog="pq", schema="w"))
    r.register_catalog(
        "pq", create_file_connector(out_root, file_format="parquet")
    )
    r.register_catalog("file", create_file_connector(str(tmp_path)))
    r.execute(
        "create table t2 as select id, name, price, x from file.src.t"
        " where id < 4"
    )
    parts = glob.glob(out_root + "/w/t2/*.parquet")
    assert len(parts) == 2  # schema part + data part
    assert r.execute("select id, name, price from t2 order by id").rows == [
        [0, "s0", 0.05],
        [1, "s1", 1.05],
        [2, "s2", 2.05],
        [3, "s3", 3.05],
    ]
    # NULLs survive the write+read cycle
    assert r.execute("select count(x) from t2").rows == [[2]]
    # INSERT appends another parquet part
    r.execute("insert into t2 select id, name, price, x from file.src.t"
              " where id = 7")
    assert r.execute("select count(*) from t2").rows == [[5]]


def test_tpch_slice_roundtrips_through_parquet(tmp_path):
    """The VERDICT done criterion, at test scale: TPC-H data written to
    parquet and read back through SQL matches the source."""
    from trino_tpu.connectors.tpch import create_tpch_connector

    out_root = str(tmp_path / "pqroot")
    r = LocalQueryRunner(Session(catalog="pq", schema="tiny"))
    r.register_catalog(
        "pq", create_file_connector(out_root, file_format="parquet")
    )
    r.register_catalog("tpch", create_tpch_connector())
    r.execute(
        "create table nation as select n_nationkey, n_name, n_regionkey"
        " from tpch.tiny.nation"
    )
    got = r.execute(
        "select n_regionkey, count(*) from nation group by 1 order by 1"
    ).rows
    want = r.execute(
        "select n_regionkey, count(*) from tpch.tiny.nation"
        " group by 1 order by 1"
    ).rows
    assert got == want


def test_corruption_and_unsupported_fail_loud(tmp_path):
    import struct

    p = str(tmp_path / "t.parquet")
    write_parquet(p, _sample_columns(), 10)
    raw = bytearray(open(p, "rb").read())
    # corrupt the footer length: the thrift parse lands mid-data
    bad = str(tmp_path / "bad.parquet")
    raw2 = bytearray(raw)
    raw2[-8:-4] = struct.pack("<I", 7)
    open(bad, "wb").write(bytes(raw2))
    with pytest.raises(Exception):
        read_parquet(bad)
    # truncated magic
    open(bad, "wb").write(bytes(raw[:-2]))
    with pytest.raises(ValueError):
        read_parquet(bad)
    # missing file
    with pytest.raises(OSError):
        read_parquet(p + ".missing")


class TestR4Features:
    """GZIP compression, dictionary pages, row-group statistics +
    predicate pruning (VERDICT r3 item #9; lib/trino-parquet)."""

    def _cols(self, n=1000, seed=3):
        import numpy as np

        from trino_tpu.connectors import parquet_format as PQ

        rng = np.random.default_rng(seed)
        return [
            PQ.ParquetColumn("k", PQ.T_INT64,
                             values=np.arange(n, dtype=np.int64)),
            PQ.ParquetColumn(
                "s", PQ.T_BYTE_ARRAY, converted=PQ.C_UTF8,
                values=[f"v{int(x)}" for x in rng.integers(0, 8, n)],
            ),
            PQ.ParquetColumn(
                "d", PQ.T_DOUBLE, values=rng.standard_normal(n),
                valid=rng.random(n) > 0.1,
            ),
        ], n

    def test_gzip_and_dictionary_roundtrip(self, tmp_path):
        import numpy as np

        from trino_tpu.connectors import parquet_format as PQ

        cols, n = self._cols()
        plain = tmp_path / "plain.parquet"
        gz = tmp_path / "gz.parquet"
        PQ.write_parquet(str(plain), cols, n, codec="none",
                         use_dictionary=False)
        PQ.write_parquet(str(gz), cols, n, codec="gzip")
        assert gz.stat().st_size < 0.6 * plain.stat().st_size
        rcols, rn = PQ.read_parquet(str(gz))
        assert rn == n
        assert np.array_equal(rcols[0].values, cols[0].values)
        got_s = [
            b.decode() if isinstance(b, (bytes, bytearray)) else b
            for b in rcols[1].values
        ]
        assert got_s == cols[1].values
        ok = rcols[2].valid
        assert np.array_equal(ok, cols[2].valid)
        assert np.allclose(
            np.asarray(rcols[2].values)[ok], np.asarray(cols[2].values)[ok]
        )

    def test_row_group_pruning(self, tmp_path):
        import numpy as np

        from trino_tpu.connectors import parquet_format as PQ

        cols, n = self._cols(n=4000)
        path = tmp_path / "rg.parquet"
        PQ.write_parquet(str(path), cols, n, codec="gzip",
                         row_group_rows=1000)
        # k in [2500, 2600]: only the third row group can match
        rcols, rn = PQ.read_parquet(
            str(path), predicate={"k": (2500, 2600)}
        )
        assert rn == 1000
        ks = np.asarray(rcols[0].values)
        assert ks.min() == 2000 and ks.max() == 2999
        # contradiction prunes everything
        rcols2, rn2 = PQ.read_parquet(
            str(path), predicate={"k": (10**9, None)}
        )
        assert rn2 == 0
