"""Replicated serving meshes (PR 17): placement, drain, failover,
host-portable checkpoints.

runtime/replicas.py carves the device set into N identical sub-meshes
and the coordinator places each mesh run on the least-loaded healthy
replica; a replica lost (MeshDeviceLost) or draining
(MeshReplicaDraining) mid-run hands its chunked query to a sibling
sub-mesh, which resumes byte-identically from the host-portable
checkpoint (recovery/checkpoint.py bytes APIs). These tests pin:

  - the grid carving (identical widths, leftover devices dropped, too
    few devices refused);
  - placement policy: least-inflight with round-robin tiebreak, breaker
    avoidance with half-open probes, exclusion exhaustion -> None;
  - the drain lifecycle: idempotent request_drain, drain_check raising
    MeshReplicaDraining off the active state, graceful drain/undrain;
  - replica failover end to end: a victim kill mid-run resumes on the
    sibling with identical rows, zero re-executed chunk steps, zero new
    XLA lowerings, and the EXPLAIN ANALYZE `replicas=` line counts it;
  - a drain requested mid-run fails over WITHOUT spending the in-run
    resume budget (MeshReplicaDraining is not in-run resumable);
  - checkpoint host portability: export_bytes on "host A", import_bytes
    into a cleared store, and a fresh runner resumes from the imported
    snapshot byte-identically;
  - the generation guard survives the host boundary: a feed-table write
    between export and import makes the imported entry unreachable (the
    run cold-starts instead of resurfacing pre-write carries);
  - deadline kills after a failover name both the resume chunk and the
    replica that picked the run up.
"""

import time

import pytest

from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import Session
from trino_tpu.parallel import mesh_chunk
from trino_tpu.parallel.mesh_chunk import (
    MeshDeviceLost,
    MeshReplicaDraining,
)
from trino_tpu.recovery import CHECKPOINTS, MeshCheckpoint
from trino_tpu.resident import GENERATIONS
from trino_tpu.runtime import DistributedQueryRunner
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_TIME_LIMIT,
    DeadlineLimits,
    ExceededTimeLimitError,
    QueryTracker,
    preemption_check,
)
from trino_tpu.runtime.replicas import ReplicaManager

# exact-valued aggregates only: a failover resume must be byte-identical
# to the uninterrupted run (same query as test_recovery.py)
Q_GROUP = (
    "select l_returnflag, l_linestatus, count(*) c, "
    "sum(l_quantity) q, min(l_orderkey) mn, max(l_orderkey) mx "
    "from lineitem group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)


def mk_runner(**session_kw):
    kw = dict(
        mesh_chunk_rows=512, mesh_checkpoint_interval_chunks=1,
    )
    kw.update(session_kw)
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", **kw),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(autouse=True)
def _clean_replica_state():
    CHECKPOINTS.clear()
    mesh_chunk.MESH_FAULT_HOOK = None
    yield
    CHECKPOINTS.clear()
    mesh_chunk.MESH_FAULT_HOOK = None


@pytest.fixture(scope="module")
def baseline_rows(tpch_cluster_mesh_off):
    # read-only query on the shared page-plane cluster (tier-1 wall)
    return tpch_cluster_mesh_off.execute(Q_GROUP).rows


# -- grid carving -------------------------------------------------------


def fake_devices(n):
    return [f"fake-dev-{i}" for i in range(n)]


def test_carving_identical_widths_drops_leftover():
    """8 devices / 3 replicas -> three rows of 2; the leftover pair
    stays out of the grid (identical widths keep checkpoints portable:
    carry shapes are (n*cap,))."""
    rm = ReplicaManager(3, devices=fake_devices(8))
    assert rm.grid.shape == (3, 2)
    assert rm.partition_width == 2
    assert rm.replicas[2].devices == ["fake-dev-4", "fake-dev-5"]
    carved = {d for rep in rm.replicas for d in rep.devices}
    assert "fake-dev-6" not in carved and "fake-dev-7" not in carved


def test_carving_refuses_too_few_devices():
    with pytest.raises(ValueError):
        ReplicaManager(5, devices=fake_devices(3))
    with pytest.raises(ValueError):
        ReplicaManager(0, devices=fake_devices(4))


# -- placement policy ---------------------------------------------------


def test_place_least_inflight_with_round_robin_tiebreak():
    rm = ReplicaManager(2, devices=fake_devices(4))
    # concurrent placements spread: the second lands on the idle sibling
    a = rm.place()
    b = rm.place()
    assert {a.replica_id, b.replica_id} == {0, 1}
    assert a.inflight == 1 and b.inflight == 1
    rm.release(a)
    rm.release(b)
    assert a.inflight == 0 and b.inflight == 0
    # sequential placements alternate on the round-robin cursor (this
    # is what warms every replica during serving warmup rounds)
    seen = []
    for _ in range(4):
        rep = rm.place()
        seen.append(rep.replica_id)
        rm.release(rep)
    assert seen == [0, 1, 0, 1]
    assert rm.placements == 6


def test_place_exhausted_exclusion_returns_none():
    rm = ReplicaManager(2, devices=fake_devices(4))
    assert rm.place(exclude=(0, 1)) is None
    assert rm.placements == 0


def test_breaker_trip_avoidance_and_half_open_probe():
    rm = ReplicaManager(
        2, devices=fake_devices(4),
        breaker_threshold=2, breaker_cooldown_s=0.5,
    )
    opens0 = METRICS.snapshot().get("replica.breaker_opens", 0.0)
    rep0 = rm.replicas[0]
    rm.report_failure(rep0)
    assert rm.breaker_states()[0] == "closed"  # below threshold
    rm.report_failure(rep0)
    assert rm.breaker_states()[0] == "open"
    assert rm.breaker_opens == 1
    assert (
        METRICS.snapshot().get("replica.breaker_opens", 0.0) - opens0 == 1
    )
    assert rm.healthy_count() == 1
    # every placement avoids the open replica while a closed one exists
    for _ in range(3):
        rep = rm.place()
        assert rep.replica_id == 1
        rm.release(rep)
    # with the sibling excluded, degrade rather than refuse: the open
    # replica still serves (mirrors _schedulable_workers)
    rep = rm.place(exclude=(1,))
    assert rep.replica_id == 0
    rm.release(rep)
    # cooldown elapsed -> half-open probe placement, success closes it
    time.sleep(0.55)
    rep = rm.place(exclude=(1,))
    assert rep.replica_id == 0
    assert rm.breaker_states()[0] == "half_open"
    rm.report_success(rep)
    rm.release(rep)
    assert rm.breaker_states()[0] == "closed"


# -- drain lifecycle ----------------------------------------------------


def test_drain_lifecycle_and_drain_check():
    rm = ReplicaManager(2, devices=fake_devices(4))
    drains0 = METRICS.snapshot().get("replica.drains", 0.0)
    rep = rm.request_drain(0)
    assert rep.state == "shutting_down"
    rm.request_drain(0)  # idempotent: no double count
    assert rm.drains == 1
    assert METRICS.snapshot().get("replica.drains", 0.0) - drains0 == 1
    # placements skip the draining replica immediately
    for _ in range(3):
        placed = rm.place()
        assert placed.replica_id == 1
        rm.release(placed)
    # in-flight chunk loops on it raise at their next boundary
    with pytest.raises(MeshReplicaDraining) as ei:
        rm.drain_check(rep)()
    assert not ei.value.in_run_resumable
    # nothing in flight -> graceful drain completes; undrain re-admits
    assert rm.drain(0, timeout_s=1.0)
    assert rep.state == "drained"
    rm.undrain(0)
    assert rep.state == "active"
    rm.drain_check(rep)()  # active again: no raise
    assert rm.stats_line() == (
        f"replicas= n=2x2 states=aa placements={rm.placements} "
        "failovers=0 drains=1 breaker_opens=0"
    )


# -- replica failover end to end ---------------------------------------


class VictimKill:
    """Kill whichever replica serves the run's first chunk, once it
    reaches `target` — victim discovery instead of a hardcoded id, so
    the round-robin placement order can never unseat the fault."""

    def __init__(self, target):
        self.target = target
        self.victim = None
        self.fired = False

    def __call__(self, k, K):
        rep = mesh_chunk.active_replica()
        if rep is None:
            return
        if self.victim is None:
            self.victim = rep
        if not self.fired and rep == self.victim and k >= self.target:
            self.fired = True
            raise MeshDeviceLost(
                f"injected: replica {rep} lost at chunk {k}/{K}"
            )


def warm_replicas(r, baseline_rows, rounds=2):
    """Sequential placements alternate replicas, so N rounds warm all N
    sub-meshes (each pays its own device-set lowering once)."""
    for _ in range(rounds):
        assert r.execute(Q_GROUP).rows == baseline_rows
        assert r._last_data_plane == "mesh", r.last_mesh_fallback
    return int(mesh_chunk.LAST_RUN_INFO["chunks"])


def test_failover_resumes_on_sibling_byte_identical(baseline_rows):
    """A replica lost at 3K/4 fails the run over to its sibling, which
    resumes from the portable checkpoint: identical rows, zero chunk
    steps re-executed (interval=1), zero new XLA lowerings (the sibling
    is warm), failover counted and visible in EXPLAIN ANALYZE."""
    r = mk_runner(mesh_replicas=2, mesh_resume_attempts=0)
    K = warm_replicas(r, baseline_rows)
    assert K >= 4, f"query too small to chunk ({K})"
    rm = r._replicas
    assert rm is not None and rm.n_replicas == 2

    target = max(1, (3 * K) // 4)
    hook = VictimKill(target)
    mesh_chunk.MESH_FAULT_HOOK = hook
    resumed0 = CHECKPOINTS.resumed
    steps0 = METRICS.snapshot().get("mesh.chunk_steps", 0.0)
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    try:
        assert r.execute(Q_GROUP).rows == baseline_rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert hook.fired
    assert r._last_data_plane == "mesh", r.last_mesh_fallback
    assert rm.failovers == 1
    assert CHECKPOINTS.resumed == resumed0 + 1
    # the sibling's runner reports the resume point; the process-wide
    # step ledger proves the query as a whole re-executed nothing
    info = mesh_chunk.LAST_RUN_INFO
    assert info["resumed_from_chunk"] == target
    steps = METRICS.snapshot().get("mesh.chunk_steps", 0.0) - steps0
    assert steps == K, f"failover re-executed {steps - K:g} chunk steps"
    compiles = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    assert compiles == 0, f"failover lowered {compiles:g} new programs"

    out = r.execute(f"EXPLAIN ANALYZE {Q_GROUP}").rows[0][0]
    assert "replicas= n=2x4 " in out
    assert "failovers=1" in out


def test_drain_mid_run_fails_over_without_resume_budget(baseline_rows):
    """request_drain on the serving replica mid-run: the chunk loop
    raises MeshReplicaDraining at the next boundary and the coordinator
    fails over DESPITE a full in-run resume budget (draining disables
    in-run resume — retrying in place would land back on the draining
    replica). The sibling finishes the query byte-identically."""
    r = mk_runner(mesh_replicas=2)  # default mesh_resume_attempts
    K = warm_replicas(r, baseline_rows)
    rm = r._replicas
    state = {"victim": None, "requested": False}

    def hook(k, K_):
        rep = mesh_chunk.active_replica()
        if rep is None:
            return
        if state["victim"] is None:
            state["victim"] = rep
        if (
            not state["requested"]
            and rep == state["victim"]
            and k >= max(1, K_ // 2)
        ):
            state["requested"] = True
            rm.request_drain(rep)

    mesh_chunk.MESH_FAULT_HOOK = hook
    resumed0 = CHECKPOINTS.resumed
    try:
        assert r.execute(Q_GROUP).rows == baseline_rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert state["requested"]
    assert r._last_data_plane == "mesh", r.last_mesh_fallback
    assert rm.failovers == 1
    assert rm.drains == 1
    assert CHECKPOINTS.resumed == resumed0 + 1
    victim = rm.replicas[state["victim"]]
    assert victim.state == "shutting_down"
    assert rm.drain(state["victim"], timeout_s=5.0)
    rm.undrain(state["victim"])


# -- checkpoint host portability ---------------------------------------


class ExportingKill:
    """At `target`, export the run's live checkpoint bytes (what a
    failing host would ship to the pod) and kill the mesh."""

    def __init__(self, target):
        self.target = target
        self.key = None
        self.data = None

    def __call__(self, k, K):
        if self.data is None and k == self.target:
            # the fixture cleared the store and interval=1 checkpoints
            # every boundary, so the single live entry is this run's
            assert len(CHECKPOINTS) == 1
            self.key = next(iter(CHECKPOINTS._entries))
            self.data = CHECKPOINTS.export_bytes(self.key)
            raise MeshDeviceLost(f"injected: host lost at chunk {k}/{K}")


def capture_checkpoint_bytes(baseline_rows):
    """Run on 'host A' (resume budget 0 -> the fault falls back to the
    page plane there), capturing the mid-run checkpoint bytes. Returns
    the receiving 'host B' runner too: B's catalogs must exist BEFORE
    the snapshot — registering a catalog bumps the global generation
    epoch (it can shadow names), which correctly fences any checkpoint
    taken under the previous epoch."""
    a = mk_runner(mesh_resume_attempts=0)
    b = mk_runner(mesh_resume_attempts=0)
    assert a.execute(Q_GROUP).rows == baseline_rows  # warm
    assert a._last_data_plane == "mesh", a.last_mesh_fallback
    K = int(mesh_chunk.LAST_RUN_INFO["chunks"])
    hook = ExportingKill(K // 2)
    mesh_chunk.MESH_FAULT_HOOK = hook
    try:
        assert a.execute(Q_GROUP).rows == baseline_rows  # page fallback
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert hook.data is not None
    assert a.last_mesh_fallback is not None, \
        "host A had no resume budget: expected the page-plane fallback"
    return b, hook.key, hook.data, K, hook.target


def test_checkpoint_bytes_resume_across_host_boundary(baseline_rows):
    """export_bytes on host A -> import_bytes into a cleared store
    ("host B") -> a FRESH runner resumes from the imported snapshot:
    identical rows, exactly the unexecuted chunks replayed. The key is
    program identity minus device identity, so B's runner finds A's
    checkpoint as its own."""
    b, key, data, K, target = capture_checkpoint_bytes(baseline_rows)
    CHECKPOINTS.clear()  # host B starts with an empty store
    assert len(CHECKPOINTS) == 0
    assert CHECKPOINTS.import_bytes(key, data)
    assert not CHECKPOINTS.import_bytes(key, b"truncated-transfer")

    resumed0 = CHECKPOINTS.resumed
    assert b.execute(Q_GROUP).rows == baseline_rows
    assert b._last_data_plane == "mesh", b.last_mesh_fallback
    assert CHECKPOINTS.resumed == resumed0 + 1
    info = mesh_chunk.LAST_RUN_INFO
    assert info["resumed_from_chunk"] == target
    assert info["executed_chunk_steps"] == K - target, \
        "host B re-executed chunks host A had already completed"


def test_imported_checkpoint_respects_local_generations(baseline_rows):
    """A feed-table write between export and import fences the imported
    entry: the receiving store's generation guard drops it on first
    `get`, so host B cold-starts instead of resurfacing pre-write
    carries. Imported bytes never bypass local DML visibility."""
    b, key, data, K, _ = capture_checkpoint_bytes(baseline_rows)
    CHECKPOINTS.clear()
    assert CHECKPOINTS.import_bytes(key, data)
    inv0 = CHECKPOINTS.invalidated
    # "DML" landing while the bytes were in flight on the host boundary:
    # bump the generation of a table the snapshot actually recorded
    fed = MeshCheckpoint.from_bytes(data).tables[0]
    GENERATIONS.bump(fed)
    assert CHECKPOINTS.get(key) is None
    assert CHECKPOINTS.invalidated == inv0 + 1

    # the run itself cold-starts and still agrees with the baseline
    CHECKPOINTS.clear()
    assert CHECKPOINTS.import_bytes(key, data)
    resumed0 = CHECKPOINTS.resumed
    assert b.execute(Q_GROUP).rows == baseline_rows
    assert b._last_data_plane == "mesh", b.last_mesh_fallback
    assert CHECKPOINTS.resumed == resumed0, \
        "a generation-fenced import must not be resumed from"
    assert mesh_chunk.LAST_RUN_INFO["executed_chunk_steps"] == K


# -- deadline kills name the failover target ---------------------------


def test_deadline_message_names_resume_replica():
    """After a failover, the chunk-boundary wall check embeds BOTH the
    resume chunk and the replica that picked the run up, keeping the
    typed [EXCEEDED_TIME_LIMIT] code."""
    tracker = QueryTracker()
    tracker.register("qr", DeadlineLimits())
    check = preemption_check(
        tracker, "qr", deadline_epoch_s=time.time() - 1.0
    )
    check.resumed_from = 7
    check.resumed_on = 1
    with pytest.raises(ExceededTimeLimitError) as ei:
        check(9, 16)
    msg = str(ei.value)
    assert EXCEEDED_TIME_LIMIT in msg
    assert "(resumed from chunk 7 on replica 1)" in msg
    assert "9/16" in msg
