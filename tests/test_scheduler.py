"""Preemptive mesh multi-tenancy (runtime/scheduler.py, PR 18).

The MeshScheduler arbitrates one mesh resource at chunk granularity:
weighted-fair virtual-time accounting between resource groups, a fast
lane whose arrivals preempt the running analytic at the next chunk
boundary, and park/resume — the preempted query's device carries
snapshot to the host checkpoint store and the query later resumes from
chunk k warm. These tests pin the scheduler invariants:

  - weighted-fair share convergence: two contending groups' completed
    chunk counts converge to their weight ratio;
  - no starvation: the lowest-weight group still progresses under a
    much heavier competitor, and an idle group REJOINS at the current
    global pass (sleeping never banks catch-up credit);
  - park byte-identity at every chunk index: wherever the fast-lane
    arrival lands, the parked-and-resumed analytic answers exactly the
    uninterrupted run's rows, with zero re-executed chunk-steps and
    zero new XLA lowerings;
  - a deadline firing WHILE PARKED kills typed (EXCEEDED_TIME_LIMIT,
    parked context in the message), the snapshot is discarded, and the
    query never resumes;
  - park-budget refusal degrades to run-to-completion — never query
    failure — and the fast waiter is served via an in-place yield;
  - drain-failover work stealing: a draining replica's unstarted chunk
    range splits across two siblings and merges byte-identically.
"""

import threading
import time

import pytest

from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import Session
from trino_tpu.parallel import mesh_chunk
from trino_tpu.recovery import CHECKPOINTS
from trino_tpu.runtime import DistributedQueryRunner
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_TIME_LIMIT,
    QueryDeadlineError,
)
from trino_tpu.runtime.scheduler import MeshScheduler, parse_group_weights

# exact-valued aggregates only: park/resume and steal-merge must be
# byte-identical to the uninterrupted run
ANALYTIC = (
    "select l_returnflag, count(*) c, sum(l_quantity) q from lineitem "
    "group by l_returnflag order by l_returnflag"
)
# dimension-decorated point lookup: serving/admission.is_fast_lane
POINT = (
    "select n_name, r_name from nation join region "
    "on n_regionkey = r_regionkey where n_nationkey = 3"
)


def mk_runner(**session_kw):
    # tiny-SF lineitem is ~7.5k rows/shard on the full-width mesh:
    # 2048-row chunks -> K=4 boundaries to preempt at
    kw = dict(mesh_chunk_rows=2048)
    kw.update(session_kw)
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", **kw),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(autouse=True)
def _clean_scheduler_state():
    CHECKPOINTS.clear()
    mesh_chunk.MESH_FAULT_HOOK = None
    yield
    CHECKPOINTS.clear()
    mesh_chunk.MESH_FAULT_HOOK = None


# -- weighted fairness (pure scheduler, synthetic chunk clock) ----------


def contend(weights, total_chunks, dt=0.01, min_slice=1):
    """Drive one MeshScheduler with one thread per group, each charging
    `dt` per synthetic chunk, until `total_chunks` complete across all
    groups. Returns per-group completed-chunk counts (only chunks run
    while the contention was live)."""
    sched = MeshScheduler(name="unit", min_slice_chunks=min_slice)
    counts = {g: 0 for g in weights}
    stop = threading.Event()
    barrier = threading.Barrier(len(weights))

    def drive(group, weight):
        job = sched.submit(f"q-{group}", group=group, weight=weight)
        # synthetic-clock harness: mark the seat ready at submit so it
        # exerts fair-share pressure even before this thread is
        # scheduled into its acquire (real queries flip ready when
        # their host prep finishes and acquire blocks)
        job.ready = True
        barrier.wait()  # all seats queued before anyone runs
        sched.acquire(job)
        try:
            done = 0
            while not stop.is_set():
                done += 1
                counts[group] += 1
                if sum(counts.values()) >= total_chunks:
                    stop.set()
                    return
                job.boundary(done, 1 << 30, dt)
        finally:
            sched.finish(job)

    threads = [
        threading.Thread(target=drive, args=(g, w), daemon=True)
        for g, w in weights.items()
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
        assert not t.is_alive(), "scheduler unit thread wedged"
    return counts, sched


def test_weighted_fair_share_converges_to_weight_ratio():
    """Two groups at weight 2:1 contending for 600 chunks complete
    chunks in ~2:1 — each chunk charges dt/weight to the holder's
    virtual-time account and the laggard preempts at the boundary."""
    counts, _ = contend({"heavy": 2.0, "light": 1.0}, 600)
    ratio = counts["heavy"] / max(counts["light"], 1)
    assert 1.6 <= ratio <= 2.6, f"expected ~2:1, got {counts}"


def test_no_starvation_of_lowest_weight_group():
    """A 50:1 weight split still grants the light group its
    proportional slices — weighted fairness shares, it never excludes."""
    counts, _ = contend({"hog": 50.0, "mouse": 1.0}, 400)
    assert counts["mouse"] >= 2, f"lowest-weight group starved: {counts}"
    assert counts["hog"] > counts["mouse"]


def test_idle_group_rejoins_at_current_pass():
    """A group that slept through 50 chunks joins at the current global
    pass — equal virtual time, no banked credit to monopolize the mesh
    paying back history."""
    sched = MeshScheduler(name="unit")
    a = sched.submit("q-busy", group="busy")
    sched.acquire(a)
    for i in range(1, 51):
        a.boundary(i, 100, 0.01)  # uncontended: keeps the grant
    b = sched.submit("q-late", group="late")
    v = sched.stats()["vtime"]
    assert v["late"] >= v["busy"] - 1e-9, (
        f"late group banked credit while idle: {v}"
    )
    sched.finish(a)
    sched.finish(b)


def test_parse_group_weights_skips_malformed_entries():
    assert parse_group_weights("etl=1,serving=4") == {
        "etl": 1.0, "serving": 4.0,
    }
    # typos must never fail dispatch: bad entries drop, good ones stay
    assert parse_group_weights("etl=x,=3,serving=2,loner") == {
        "serving": 2.0,
    }
    assert parse_group_weights("") == {}


# -- park/resume on the real mesh ---------------------------------------


def spawn_point_at(r, sched, target, state):
    """MESH_FAULT_HOOK: at analytic chunk `target`, start POINT on a
    side thread and hold the boundary until its fast-lane seat is
    visible in the run queue — the NEXT boundary then parks
    deterministically."""
    main = threading.current_thread()

    def hook(k, K):
        if threading.current_thread() is not main:
            return  # the point lookup's own chunk loop
        if state["fired"] or k != target:
            return
        state["fired"] = True

        def run_point():
            state["point_rows"] = r.execute(POINT).rows

        threading.Thread(target=run_point, daemon=True).start()
        deadline = time.monotonic() + 10.0
        while (
            sched.waiting_count(fast=True) < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.002)

    return hook


def await_point(state, timeout=10.0):
    deadline = time.monotonic() + timeout
    while state["point_rows"] is None and time.monotonic() < deadline:
        time.sleep(0.002)
    return state["point_rows"]


def test_park_byte_identity_at_every_chunk_index():
    """Wherever the fast-lane lookup lands (park at chunk 1..K-1), the
    preempted analytic resumes to exactly the uninterrupted rows, with
    zero re-executed chunk-steps and zero new XLA lowerings."""
    r = mk_runner()
    clean = r.execute(ANALYTIC).rows  # warm analytic
    K = int(mesh_chunk.LAST_RUN_INFO["chunks"])
    assert K >= 3, f"query too small to exercise every index ({K})"
    point_clean = r.execute(POINT).rows  # warm point shape
    sched = r._mesh_scheduler
    assert sched is not None, "scheduled dispatch did not engage"
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)

    for target in range(K - 1):  # hook at k parks at boundary k+1
        state = {"fired": False, "point_rows": None}
        parks0, resumes0 = sched.parks, sched.resumes
        steps0 = METRICS.snapshot().get("mesh.chunk_steps", 0.0)
        mesh_chunk.MESH_FAULT_HOOK = spawn_point_at(
            r, sched, target, state
        )
        try:
            rows = r.execute(ANALYTIC).rows
        finally:
            mesh_chunk.MESH_FAULT_HOOK = None
        assert state["fired"], f"hook never fired at chunk {target}"
        assert rows == clean, f"park at chunk {target + 1} changed rows"
        info = mesh_chunk.LAST_RUN_INFO
        assert info["parks"] == 1 and info["unparks"] == 1, info
        assert info["executed_chunk_steps"] == K, (
            f"re-executed chunk-steps after park at {target + 1}: {info}"
        )
        assert sched.parks == parks0 + 1
        assert sched.resumes == resumes0 + 1
        assert await_point(state) == point_clean
        # analytic K steps + the point lookup's own single chunk
        steps = METRICS.snapshot().get("mesh.chunk_steps", 0.0) - steps0
        assert steps == K + 1, f"unexpected step ledger delta {steps:g}"

    compiles = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    assert compiles == 0, (
        f"park/resume cycles lowered {compiles:g} new XLA programs"
    )
    assert CHECKPOINTS.parked_count() == 0, "leaked parked snapshot"


def test_deadline_while_parked_kills_typed_and_never_resumes():
    """A wall deadline expiring while the query sits PARKED raises the
    typed EXCEEDED_TIME_LIMIT error out of the parked wait — with the
    parked context in the message — discards the snapshot, and the
    query never resumes. The occupying fast seat is synthetic, so the
    park wait provably outlives the budget."""
    r = mk_runner()
    clean = r.execute(ANALYTIC).rows  # warm
    sched = r._mesh_scheduler
    main = threading.current_thread()
    state = {"fake": None}

    def hook(k, K):
        if threading.current_thread() is not main:
            return
        if state["fake"] is None and k == 1:
            # a fast seat that never runs: the analytic parks at the
            # next boundary and stays parked until the deadline fires
            state["fake"] = sched.submit("fake-point", fast=True)
            # synthetic waiter: never calls acquire, so mark it ready
            # by hand — only ready waiters exert preemption pressure
            state["fake"].ready = True

    # slow the tracker tick so the park-wait poll — not the background
    # enforcement thread — is what kills the query
    r.query_tracker.tick_interval_s = 60.0
    r.session.query_max_execution_time_s = 0.5
    parks0, resumes0 = sched.parks, sched.resumes
    mesh_chunk.MESH_FAULT_HOOK = hook
    try:
        with pytest.raises(QueryDeadlineError) as ei:
            r.execute(ANALYTIC)
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
        if state["fake"] is not None:
            sched.finish(state["fake"])
    msg = str(ei.value)
    assert EXCEEDED_TIME_LIMIT in msg
    assert "parked" in msg, f"no parked context in kill message: {msg}"
    assert sched.parks == parks0 + 1
    assert sched.resumes == resumes0, "a dead query must never resume"
    assert CHECKPOINTS.parked_count() == 0, "kill must discard the park"

    # the rerun starts FRESH — no resume from the dead query's state
    r.session.query_max_execution_time_s = 0.0
    assert r.execute(ANALYTIC).rows == clean
    info = mesh_chunk.LAST_RUN_INFO
    assert info["resumes"] == 0 and info["parks"] == 0, info


def test_park_budget_refusal_degrades_to_run_to_completion():
    """park_max_bytes too small for the snapshot: the park is REFUSED,
    the analytic keeps its carries and completes correctly (degradation
    is never query failure), and the fast waiter is served via an
    in-place yield instead."""
    r = mk_runner(park_max_bytes=1)
    clean = r.execute(ANALYTIC).rows  # warm
    K = int(mesh_chunk.LAST_RUN_INFO["chunks"])
    assert K >= 4, f"need a boundary after the refusal to yield at ({K})"
    point_clean = r.execute(POINT).rows
    sched = r._mesh_scheduler
    state = {"fired": False, "point_rows": None}
    refusals0, yields0, parks0 = (
        sched.park_refusals, sched.yields, sched.parks,
    )
    mesh_chunk.MESH_FAULT_HOOK = spawn_point_at(r, sched, 1, state)
    try:
        rows = r.execute(ANALYTIC).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert state["fired"]
    assert rows == clean, "budget refusal must not change the answer"
    info = mesh_chunk.LAST_RUN_INFO
    assert info["parks"] == 0 and info["unparks"] == 0, info
    assert sched.park_refusals == refusals0 + 1
    assert sched.parks == parks0
    assert sched.yields >= yields0 + 1, (
        "fast waiter not served via in-place yield after refusal"
    )
    assert await_point(state) == point_clean
    assert CHECKPOINTS.parked_count() == 0


# -- drain-failover work stealing ---------------------------------------


def test_drain_steal_splits_unstarted_chunks_across_siblings():
    """A replica draining mid-run on an all-append-carry query: the
    coordinator splits the unstarted chunk range across TWO siblings —
    the primary resumes [k0, mid) from the portable checkpoint while a
    helper computes [mid, K) from zero carries — and the merge is
    byte-identical with nothing re-executed."""
    r = mk_runner(
        mesh_replicas=4, mesh_chunk_rows=64,
        mesh_checkpoint_interval_chunks=1,
    )
    # scan-filter: every carry is an append accumulator ("out"), the
    # steal-eligible shape (group carries cannot merge byte-identically)
    q = ("select l_orderkey, l_linenumber from lineitem "
         "where l_quantity < 4")
    rows0 = None
    for _ in range(4):  # round-robin placement: warm all four replicas
        rows = r.execute(q).rows
        assert r._last_data_plane == "mesh", r.last_mesh_fallback
        if rows0 is None:
            rows0 = rows
        else:
            assert rows == rows0
    K = int(mesh_chunk.LAST_RUN_INFO["chunks"])
    assert K >= 6, f"query too small to split ({K})"
    rm = r._replicas
    assert rm is not None and rm.n_replicas == 4
    state = {"victim": None, "requested": False}

    def hook(k, K_):
        rep = mesh_chunk.active_replica()
        if rep is None:
            return
        if state["victim"] is None:
            state["victim"] = rep
        if (
            not state["requested"]
            and rep == state["victim"]
            and k >= max(1, K_ // 2)
        ):
            state["requested"] = True
            rm.request_drain(rep)

    steals0 = METRICS.snapshot().get("scheduler.steals", 0.0)
    steps0 = METRICS.snapshot().get("mesh.chunk_steps", 0.0)
    mesh_chunk.MESH_FAULT_HOOK = hook
    try:
        rows = r.execute(q).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert state["requested"]
    assert rows == rows0, "steal merge changed the answer"
    assert rm.failovers == 1
    info = mesh_chunk.LAST_RUN_INFO
    assert info["steals"] == 1, f"steal did not complete: {info}"
    assert (
        METRICS.snapshot().get("scheduler.steals", 0.0) == steals0 + 1
    )
    # victim [0, k0) + primary [k0, mid) + helper [mid, K): the whole
    # query executes exactly K chunk-steps across three replicas
    steps = METRICS.snapshot().get("mesh.chunk_steps", 0.0) - steps0
    assert steps == K, f"steal re-executed {steps - K:g} chunk-steps"
    out = r.execute(f"EXPLAIN ANALYZE {q}").rows[0][0]
    assert "steals=1" in out
