"""Resident state tier (trino_tpu/resident/): generation clock, pin
manager LRU/budget/pool accounting, the device probe table with delta
maintenance + compaction, and the serving fast lane end-to-end against
the ordinary execute path as oracle."""

import numpy as np
import pytest

from trino_tpu.resident.manager import (
    GENERATIONS,
    RESIDENT,
    ResidentStateManager,
    TableGenerations,
    table_key,
)
from trino_tpu.resident.table import PROBE_OUT_CAP, ResidentTable


# -- TableGenerations ---------------------------------------------------


class TestGenerations:
    def test_bump_changes_snapshot(self):
        g = TableGenerations()
        k = table_key("c", "s", "t")
        s0 = g.snapshot([k])
        g.bump(k)
        assert g.snapshot([k]) != s0
        # an unrelated table's clock is untouched
        other = table_key("c", "s", "u")
        assert g.get(other) == (0, 0)

    def test_epoch_bump_invalidates_every_snapshot(self):
        g = TableGenerations()
        a, b = table_key("c", "s", "a"), table_key("c", "s", "b")
        sa, sb = g.snapshot([a]), g.snapshot([b])
        g.bump_all()
        assert g.snapshot([a]) != sa and g.snapshot([b]) != sb

    def test_snapshot_is_order_insensitive(self):
        g = TableGenerations()
        a, b = table_key("c", "s", "a"), table_key("c", "s", "b")
        assert g.snapshot([a, b]) == g.snapshot([b, a])


# -- ResidentStateManager ----------------------------------------------


class TestManager:
    def test_pin_lookup_evict(self):
        m = ResidentStateManager(budget_bytes=1 << 20)
        t = table_key("c", "s", "t")
        assert m.pin(("k1",), "payload", 100, [t], index_key=("i1",))
        assert m.lookup(("k1",)) == "payload"
        assert m.find(("i1",)) == (("k1",), "payload")
        assert m.evict(("k1",))
        assert m.lookup(("k1",)) is None
        assert m.find(("i1",)) is None
        assert m.stats()["hits"] == 1 and m.stats()["misses"] == 1

    def test_lru_eviction_under_budget(self):
        m = ResidentStateManager(budget_bytes=250)
        t = table_key("c", "s", "t")
        m.pin(("a",), 1, 100, [t])
        m.pin(("b",), 2, 100, [t])
        m.lookup(("a",))  # touch: "b" becomes LRU
        m.pin(("c",), 3, 100, [t])
        assert m.lookup(("b",)) is None
        assert m.lookup(("a",)) == 1 and m.lookup(("c",)) == 3
        assert m.pinned_bytes <= 250

    def test_oversized_pin_refused_not_raised(self):
        m = ResidentStateManager(budget_bytes=50)
        assert not m.pin(("big",), 1, 100, [table_key("c", "s", "t")])
        assert len(m) == 0 and m.stats()["pin_rejects"] == 1

    def test_invalidate_table_is_table_granular(self):
        m = ResidentStateManager(budget_bytes=1 << 20)
        t1, t2 = table_key("c", "s", "t1"), table_key("c", "s", "t2")
        m.pin(("a",), 1, 10, [t1])
        m.pin(("b",), 2, 10, [t2])
        m.pin(("ab",), 3, 10, [t1, t2])  # multi-table entry
        assert m.invalidate_table(t1) == 2
        assert m.lookup(("b",)) == 2
        assert m.lookup(("a",)) is None and m.lookup(("ab",)) is None

    def test_rekey_keeps_entry_warm_and_index_current(self):
        m = ResidentStateManager(budget_bytes=1 << 20)
        t = table_key("c", "s", "t")
        m.pin(("k", 1), "p", 10, [t], index_key=("i",))
        assert m.rekey(("k", 1), ("k", 2))
        assert m.lookup(("k", 1)) is None
        assert m.lookup(("k", 2)) == "p"
        assert m.find(("i",)) == (("k", 2), "p")

    def test_set_bytes_recharges(self):
        m = ResidentStateManager(budget_bytes=1 << 20)
        m.pin(("k",), "p", 100, [table_key("c", "s", "t")])
        m.set_bytes(("k",), 300)
        assert m.pinned_bytes == 300
        m.set_bytes(("k",), 50)
        assert m.pinned_bytes == 50

    def test_pool_charge_and_revocation(self):
        from trino_tpu.runtime.memory import MemoryPool

        pool = MemoryPool(max_bytes=10_000)
        m = ResidentStateManager(budget_bytes=1 << 20)
        m.pin(("k",), "p", 4_000, [table_key("c", "s", "t")])
        m.attach_pool(pool)
        assert pool.reserved_bytes >= 4_000
        # a query wanting more than what's free revokes the pins BEFORE
        # the pool fails the reservation
        pool.reserve(8_000, query_id="q1")
        assert len(m) == 0 and m.stats()["revocations"] == 1
        pool.free(8_000, query_id="q1")
        m.detach_pool()
        assert pool.reserved_bytes == 0


# -- ResidentTable ------------------------------------------------------


def _kv_table(n=40, delta_max=8, string_key=False):
    keys = [f"k{i}" for i in range(n)] if string_key else list(range(n))
    rows = [[i * 10] for i in range(n)]
    return ResidentTable(
        "k", ["v"], ["bigint"], keys, rows,
        string_key=string_key, delta_max_rows=delta_max,
    )


class TestResidentTable:
    def test_probe_int_key(self):
        t = _kv_table()
        assert t.probe(7) == [[70]]
        assert t.probe(39) == [[390]]
        assert t.probe(12345) == []

    def test_probe_string_key(self):
        t = _kv_table(string_key=True)
        assert t.probe("k3") == [[30]]
        # never-encoded key short-circuits on the host dictionary
        assert t.probe("nope") == []

    def test_duplicate_keys_return_all_rows_fanout_bails(self):
        keys = [1] * 3 + [2] * (PROBE_OUT_CAP + 1)
        rows = [[i] for i in range(len(keys))]
        t = ResidentTable("k", ["v"], ["bigint"], keys, rows,
                          string_key=False)
        assert t.probe(1) == [[0], [1], [2]]
        # past the probe rung: None = caller falls to the cold path
        assert t.probe(2) is None

    def test_delta_append_then_compact(self):
        t = _kv_table(n=40, delta_max=8)
        cap0 = t.base_cap
        assert t.delta_room(2)
        assert t.append_delta([100, 101], [[1000], [1010]])
        # probes see base + delta before compaction
        assert t.probe(100) == [[1000]]
        assert t.probe(7) == [[70]]
        assert t.append_delta([102, 103], [[1020], [1030]])
        assert t.wants_compaction()
        t.compact()
        assert t.delta_count == 0
        for k, v in [(100, 1000), (103, 1030), (7, 70)]:
            assert t.probe(k) == [[v]]
        # 44 live rows still fit the original rung: no rekey needed
        assert t.base_cap == cap0 and t.base_live == 44

    def test_delta_overflow_refused(self):
        t = _kv_table(n=4, delta_max=2)
        assert not t.append_delta(list(range(100, 103)),
                                  [[0], [0], [0]])
        assert t.probe(1) == [[10]]  # table unharmed

    def test_device_bytes_tracks_delta(self):
        t = _kv_table()
        b0 = t.device_bytes
        t.append_delta([500], [[5000]])
        assert t.device_bytes > b0


# -- fast lane end-to-end ----------------------------------------------


@pytest.fixture()
def kv_runner():
    from trino_tpu import types as Ty
    from trino_tpu.connectors.memory import create_memory_connector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.engine import LocalQueryRunner, Session

    mem = create_memory_connector()
    r = LocalQueryRunner(Session(
        catalog="memory", schema="s",
        resident_tables="s.kv", resident_delta_max_rows=32,
    ))
    r.register_catalog("memory", mem)
    n = 100
    rng = np.random.default_rng(11)
    mem.load_table(
        "s", "kv",
        [ColumnMetadata("k", Ty.BIGINT), ColumnMetadata("v", Ty.BIGINT)],
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 1 << 20, n).astype(np.int64)],
    )
    RESIDENT.evict_all()
    yield r
    RESIDENT.evict_all()


def _fast(r, k):
    from trino_tpu.resident.fastlane import try_resident_lookup

    res = try_resident_lookup(r, f"select v from kv where k = {k}")
    return None if res is None else res.rows


class TestFastLane:
    def test_build_then_hit(self, kv_runner):
        r = kv_runner
        want = r.execute("select v from kv where k = 7").rows
        assert _fast(r, 7) == want  # cold build
        pins0 = RESIDENT.stats()["pins"]
        assert _fast(r, 7) == want  # pinned hit
        assert _fast(r, 42) == r.execute(
            "select v from kv where k = 42"
        ).rows
        assert RESIDENT.stats()["pins"] == pins0  # no rebuild

    def test_unconfigured_table_declines(self, kv_runner):
        r = kv_runner
        r.session.resident_tables = "s.other"
        assert _fast(r, 7) is None

    def test_non_point_lookup_declines(self, kv_runner):
        from trino_tpu.resident.fastlane import try_resident_lookup

        assert try_resident_lookup(
            kv_runner, "select sum(v) from kv"
        ) is None

    def test_update_invalidates_and_rebuilds(self, kv_runner):
        r = kv_runner
        assert _fast(r, 7)  # pin
        r.execute("update kv set v = -5 where k = 7")
        assert _fast(r, 7) == [[-5]]
        assert _fast(r, 7) == r.execute(
            "select v from kv where k = 7"
        ).rows

    def test_insert_rides_delta_without_repin(self, kv_runner):
        from trino_tpu.resident.fastlane import drain_compactions

        r = kv_runner
        assert _fast(r, 7)  # pin
        pins0 = RESIDENT.stats()["pins"]
        r.execute("insert into kv values (500, 5000)")
        assert _fast(r, 500) == [[5000]]
        assert _fast(r, 7) == r.execute(
            "select v from kv where k = 7"
        ).rows
        # the append re-keyed the live pin; it did not rebuild
        assert RESIDENT.stats()["pins"] == pins0
        # push past half the delta budget -> background compaction
        for i in range(501, 501 + 20):
            r.execute(f"insert into kv values ({i}, {i * 10})")
        drain_compactions()
        assert _fast(r, 510) == [[5100]]
        assert _fast(r, 7) == r.execute(
            "select v from kv where k = 7"
        ).rows

    def test_zero_budget_degrades_to_cold_path(self, kv_runner):
        r = kv_runner
        r.session.resident_pin_budget_mb = 0
        RESIDENT.evict_all()
        want = r.execute("select v from kv where k = 3").rows
        assert _fast(r, 3) == want  # served, transient build
        assert len(RESIDENT) == 0  # nothing stayed pinned
        # restore the default so later tests see a sane budget
        RESIDENT.configure(64 << 20)
