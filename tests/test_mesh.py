"""Mesh-resident execution tests: the SQL data plane over ICI collectives.

Verifies VERDICT r1 item #1: distributed TPC-H runs through ONE
shard_map program per query whose hash exchanges are lax.all_to_all
over the 8-device mesh (parallel/mesh_plan.py), with results matching
the sqlite oracle. The full 22-query sweep runs in the dev loop
(all 22 verified); this suite keeps a representative subset green in CI:
r3: the CI sweep covers ALL 22 queries (VERDICT r2 weak #4 — the
README claimed 22 but CI asserted 8), each with a counter assert that
the query executed through the mesh plane.
PR2: the full sweep is ~4 min wall — too heavy for the 870s tier-1
budget, so the heavy queries carry @pytest.mark.slow; the dev loop
still runs all 22.
PR10 (chunked mesh plane): per-query cold walls recorded in
MULTICHIP_r06.json put ten queries at <=7s each, so the un-slow-marked
set widens from q1/q6 to {1,3,5,6,11,12,14,19,20,22} (~35s added,
well inside the tier-1 budget); the rest stay slow-marked."""

import pytest

from tests.oracle import assert_rows_match, sqlite_rows
from tests.test_tpch import to_sqlite
from tests.tpch_queries import QUERIES
from trino_tpu.parallel import mesh_plan

SF = 0.01
FAST_MESH_QUERIES = (1, 3, 5, 6, 11, 12, 14, 19, 20, 22)
MESH_QUERIES = [
    q if q in FAST_MESH_QUERIES else pytest.param(q, marks=pytest.mark.slow)
    for q in range(1, 23)
]


@pytest.fixture(scope="module")
def oracle():
    import sqlite3

    from tests.oracle import load_tpch_sqlite

    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def runner(tpch_cluster):
    return tpch_cluster


@pytest.mark.parametrize("qid", MESH_QUERIES)
def test_mesh_tpch(qid, runner, oracle):
    sql = QUERIES[qid]
    before = dict(mesh_plan.MESH_COUNTERS)
    res = runner.execute(sql)
    after = mesh_plan.MESH_COUNTERS
    # the query must have executed through the mesh data plane
    assert after["queries"] == before["queries"] + 1, "query fell back to HTTP"
    expected = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(
        res.rows, expected, ordered=("order by" in sql), abs_tol=1e-2
    )


def test_mesh_uses_all_to_all(runner):
    """The FIXED_HASH exchange rides lax.all_to_all (not host pages)."""
    before = mesh_plan.MESH_COUNTERS["all_to_all"]
    runner.execute(
        "select l_returnflag, count(*) from lineitem group by l_returnflag"
    )
    assert mesh_plan.MESH_COUNTERS["all_to_all"] > before


def test_mesh_broadcast_uses_all_gather(runner):
    before = mesh_plan.MESH_COUNTERS["all_gather"]
    runner.execute(
        "select n_name, count(*) from supplier, nation "
        "where s_nationkey = n_nationkey group by n_name"
    )
    assert mesh_plan.MESH_COUNTERS["all_gather"] > before


def test_mesh_program_contains_collective():
    """Structural check: the compiled exchange lowers to an all_to_all
    collective in the jaxpr (the VERDICT 'assert via jaxpr' form)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from trino_tpu import types as T
    from trino_tpu.block import Column, RelBatch
    from trino_tpu.parallel.mesh_plan import AXIS, _exchange_hash
    from trino_tpu.jaxcfg import get_shard_map

    shard_map = get_shard_map()
    if shard_map is None:
        pytest.skip("shard_map unavailable in this jax")

    devs = jax.devices()
    mesh = Mesh(np.array(devs), (AXIS,))
    n = len(devs)

    def body(data):
        batch = RelBatch(
            [Column(T.BIGINT, data, jnp.ones_like(data, dtype=jnp.bool_))],
            jnp.ones_like(data, dtype=jnp.bool_),
        )
        out = _exchange_hash(batch, [0], n)
        return out.columns[0].data

    from jax.sharding import PartitionSpec as PSpec

    f = shard_map(
        body, mesh=mesh, in_specs=(PSpec(AXIS),), out_specs=PSpec(AXIS),
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(f)(jnp.arange(16 * n, dtype=jnp.int64))
    assert "all_to_all" in str(jaxpr)


def test_mesh_window_runs_on_mesh(runner):
    """r4: partitioned window functions mesh-compile (partition-local
    after the all_to_all repartition — mesh_plan._visit_WindowNode)."""
    before = mesh_plan.MESH_COUNTERS["queries"]
    res = runner.execute(
        "select o_custkey, row_number() over "
        "(partition by o_custkey order by o_orderkey) rn "
        "from orders where o_custkey < 10"
    )
    assert mesh_plan.MESH_COUNTERS["queries"] == before + 1
    assert len(res.rows) > 0


def test_mesh_fallback_on_unsupported(runner):
    """r4 closed the plan-shape gaps (windows, offsets, distinct via
    single-step gather), so the remaining deterministic MeshUnsupported
    is a plan with no distributed fragment at all. The coordinator must
    fall back to the page-exchange path, still answer correctly, and
    record WHY (observable fallback)."""
    before = dict(mesh_plan.MESH_COUNTERS)
    res = runner.execute("select 1")
    assert mesh_plan.MESH_COUNTERS["queries"] == before["queries"]
    assert mesh_plan.MESH_COUNTERS["fallbacks"] == before["fallbacks"] + 1
    assert runner.last_mesh_fallback is not None
    assert len(res.rows) > 0


def test_mesh_empty_result(runner):
    res = runner.execute(
        "select l_returnflag, sum(l_quantity) from lineitem "
        "where l_quantity > 1000000 group by l_returnflag"
    )
    assert res.rows == []


def test_mesh_null_join_keys(runner):
    """NULL keys never match in joins, across the exchange too."""
    res = runner.execute(
        "select count(*) from orders o, customer c "
        "where o.o_custkey = c.c_custkey and o.o_custkey is null"
    )
    assert res.rows[0][0] == 0


def test_mesh_window_over_partition_keys(runner, oracle):
    """Window functions run ON the mesh when PARTITION BY keys hash-
    distribute: partition-local compute after the all_to_all (VERDICT
    r3 item #4; AddExchanges window distribution)."""
    sql = (
        "select s_nationkey, s_name, "
        "sum(s_acctbal) over (partition by s_nationkey) tot, "
        "row_number() over (partition by s_nationkey order by s_name) rn "
        "from supplier order by s_nationkey, s_name"
    )
    before = dict(mesh_plan.MESH_COUNTERS)
    res = runner.execute(sql)
    after = mesh_plan.MESH_COUNTERS
    assert after["queries"] == before["queries"] + 1, "fell back to HTTP"
    expected = sqlite_rows(
        oracle,
        "select s_nationkey, s_name, "
        "sum(s_acctbal) over (partition by s_nationkey) tot, "
        "row_number() over (partition by s_nationkey order by s_name) rn "
        "from supplier order by s_nationkey, s_name",
    )
    assert_rows_match(res.rows, expected, ordered=True, abs_tol=1e-2)


def test_mesh_offset_only_limit(runner, oracle):
    sql = "select n_name from nation order by n_name offset 5"
    before = dict(mesh_plan.MESH_COUNTERS)
    res = runner.execute(sql)
    expected = sqlite_rows(
        oracle, "select n_name from nation order by n_name limit -1 offset 5"
    )
    assert_rows_match(res.rows, expected, ordered=True)
