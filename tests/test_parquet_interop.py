"""Parquet SNAPPY/ZSTD + nested LIST interop — VERDICT r4 item #9.

Cross-engine both directions against pyarrow (the "another engine"
fixture writer the VERDICT asked for): pyarrow-written SNAPPY/ZSTD
files with nested list columns read correctly, and files THIS codec
writes read back identically in pyarrow. The pure-python SNAPPY codec
(utils/snappy.py) is validated byte-level against pyarrow's."""

import os
import tempfile

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from trino_tpu.connectors import parquet_format as PQ
from trino_tpu.utils import snappy


TAGS = [[1, 2], [], None, [5, None, 7]]

try:
    import zstandard  # noqa: F401

    _HAS_ZSTD = True
except ImportError:
    _HAS_ZSTD = False

# zstd rides on the optional `zstandard` package; containers without it
# must skip, not fail (snappy/gzip coverage stands on its own)
_codec_param = lambda c: (  # noqa: E731
    pytest.param(c, marks=pytest.mark.skipif(
        not _HAS_ZSTD, reason="zstandard not installed"
    )) if c == "zstd" else c
)


def _fixture_table():
    return pa.table({
        "id": pa.array([1, 2, 3, 4], pa.int64()),
        "tags": pa.array(TAGS, pa.list_(pa.int64())),
        "name": pa.array(["a", "bb", None, "dd"]),
        "score": pa.array([1.5, None, 3.5, 4.0], pa.float64()),
    })


def _write_pa(codec):
    f = tempfile.mktemp(suffix=".parquet")
    pq.write_table(
        _fixture_table(), f, compression=codec, use_dictionary=False,
        write_statistics=False, data_page_version="1.0",
    )
    return f


class TestSnappyCodec:
    def test_bidirectional_vs_pyarrow(self):
        import random

        random.seed(3)
        for payload in (b"", b"x", b"ab" * 4000,
                        bytes(random.randbytes(5000)), b"\0" * 65536):
            mine = snappy.compress(payload)
            assert bytes(pa.decompress(
                mine, decompressed_size=len(payload), codec="snappy"
            )) == payload
            theirs = pa.compress(payload, codec="snappy", asbytes=True)
            assert snappy.decompress(theirs) == payload


class TestReadForeignFiles:
    @pytest.mark.parametrize("codec", [_codec_param(c) for c in ("snappy", "zstd", "gzip")])
    def test_read_pyarrow_nested(self, codec):
        f = _write_pa(codec)
        try:
            cols, n = PQ.read_parquet(f)
            by = {c.name: c for c in cols}
            assert n == 4
            tags = by["tags"]
            assert list(tags.list_lengths) == [2, 0, 0, 3]
            assert list(tags.valid) == [True, True, False, True]
            assert list(tags.element_valid) == [
                True, True, True, False, True
            ]
            dense = [
                v for v, ok in zip(tags.values, tags.element_valid) if ok
            ]
            assert dense == [1, 2, 5, 7]
            assert by["id"].values.tolist() == [1, 2, 3, 4]
            assert by["score"].valid.tolist() == [
                True, False, True, True
            ]
        finally:
            os.unlink(f)


class TestWriteForeignReadable:
    @pytest.mark.parametrize("codec", [_codec_param(c) for c in ("snappy", "zstd", "gzip")])
    def test_pyarrow_reads_our_files(self, codec):
        src = _write_pa("snappy")
        out = tempfile.mktemp(suffix=".parquet")
        try:
            cols, n = PQ.read_parquet(src)
            PQ.write_parquet(out, cols, n, codec=codec)
            t = pq.read_table(out)
            assert t.column("id").to_pylist() == [1, 2, 3, 4]
            assert t.column("tags").to_pylist() == TAGS
            names = t.column("name").to_pylist()
            names = [
                x.decode() if isinstance(x, bytes) else x for x in names
            ]
            assert names == ["a", "bb", None, "dd"]
            assert t.column("score").to_pylist() == [1.5, None, 3.5, 4.0]
        finally:
            os.unlink(src)
            if os.path.exists(out):
                os.unlink(out)

    @pytest.mark.skipif(not _HAS_ZSTD, reason="zstandard not installed")
    def test_self_round_trip_row_groups(self):
        src = _write_pa("snappy")
        out = tempfile.mktemp(suffix=".parquet")
        try:
            cols, n = PQ.read_parquet(src)
            PQ.write_parquet(
                out, cols, n, codec="zstd", row_group_rows=2
            )
            cols2, n2 = PQ.read_parquet(out)
            assert n2 == 4
            tg = {c.name: c for c in cols2}["tags"]
            assert list(tg.list_lengths) == [2, 0, 0, 3]
            assert list(tg.element_valid) == [
                True, True, True, False, True
            ]
        finally:
            os.unlink(src)
            if os.path.exists(out):
                os.unlink(out)
