"""Composite aggregate library: variance/covariance/moment family,
boolean/conditional aggregates, approx_distinct — lowered by the
analyzer onto shared sum/count/min/max primitives plus a finisher
projection (the accumulator-state analogue of Trino's
main/operator/aggregation/ library, e.g. VarianceState; SURVEY.md
§2.6 "Aggregation functions")."""

import math

import numpy as np
import pytest

from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


V = "(VALUES (1.0), (2.0), (3.0), (4.0), (10.0)) t(x)"
X = np.array([1.0, 2.0, 3.0, 4.0, 10.0])

PAIRS = "(VALUES (1.0, 2.0), (2.0, 3.5), (3.0, 3.0), (4.0, 8.0)) t(y, x)"
YS = np.array([1.0, 2.0, 3.0, 4.0])
XS = np.array([2.0, 3.5, 3.0, 8.0])


def _one(runner, sql):
    return runner.execute(sql).only_value()


CASES = [
    (f"SELECT var_samp(x) FROM {V}", np.var(X, ddof=1)),
    (f"SELECT var_pop(x) FROM {V}", np.var(X)),
    (f"SELECT variance(x) FROM {V}", np.var(X, ddof=1)),
    (f"SELECT stddev_samp(x) FROM {V}", np.std(X, ddof=1)),
    (f"SELECT stddev_pop(x) FROM {V}", np.std(X)),
    (f"SELECT stddev(x) FROM {V}", np.std(X, ddof=1)),
    (f"SELECT geometric_mean(x) FROM {V}", float(np.exp(np.mean(np.log(X))))),
    (f"SELECT covar_samp(y, x) FROM {PAIRS}", float(np.cov(YS, XS, ddof=1)[0, 1])),
    (
        f"SELECT covar_pop(y, x) FROM {PAIRS}",
        float(((YS - YS.mean()) * (XS - XS.mean())).mean()),
    ),
    (f"SELECT corr(y, x) FROM {PAIRS}", float(np.corrcoef(YS, XS)[0, 1])),
    (
        f"SELECT regr_slope(y, x) FROM {PAIRS}",
        float(np.cov(YS, XS, ddof=1)[0, 1] / np.var(XS, ddof=1)),
    ),
    (
        f"SELECT regr_intercept(y, x) FROM {PAIRS}",
        float(
            YS.mean()
            - (np.cov(YS, XS, ddof=1)[0, 1] / np.var(XS, ddof=1)) * XS.mean()
        ),
    ),
]


@pytest.mark.parametrize("sql,want", CASES)
def test_numeric_aggregate(sql, want, runner):
    got = _one(runner, sql)
    assert got == pytest.approx(want, rel=1e-9, abs=1e-9), sql


def test_moments(runner):
    n = len(X)
    m = X.mean()
    m2 = ((X - m) ** 2).sum()
    m3 = ((X - m) ** 3).sum()
    m4 = ((X - m) ** 4).sum()
    skew = math.sqrt(n) * m3 / m2**1.5
    kurt = (
        n * (n + 1) * (n - 1) / ((n - 2) * (n - 3)) * m4 / m2**2
        - 3 * (n - 1) ** 2 / ((n - 2) * (n - 3))
    )
    assert _one(runner, f"SELECT skewness(x) FROM {V}") == pytest.approx(skew)
    assert _one(runner, f"SELECT kurtosis(x) FROM {V}") == pytest.approx(kurt)


def test_boolean_and_conditional_aggregates(runner):
    b = "(VALUES (true), (false), (NULL), (true)) t(b)"
    assert _one(runner, f"SELECT bool_and(b) FROM {b}") is False
    assert _one(runner, f"SELECT bool_or(b) FROM {b}") is True
    assert _one(runner, f"SELECT every(b) FROM {b}") is False
    assert _one(runner, f"SELECT count_if(b) FROM {b}") == 2
    t = "(VALUES (true), (true)) t(b)"
    assert _one(runner, f"SELECT bool_and(b) FROM {t}") is True
    # count_if over an empty relation is 0, not NULL
    assert (
        _one(runner, "SELECT count_if(b) FROM (VALUES (true)) t(b) WHERE b = false")
        == 0
    )


def test_null_and_small_n_semantics(runner):
    one = "(VALUES (42.0)) t(x)"
    assert _one(runner, f"SELECT var_samp(x) FROM {one}") is None
    assert _one(runner, f"SELECT var_pop(x) FROM {one}") == 0.0
    assert _one(runner, f"SELECT stddev_samp(x) FROM {one}") is None
    empty = "(VALUES (1.0)) t(x) WHERE x < 0"
    assert _one(runner, f"SELECT var_pop(x) FROM {empty}") is None
    assert _one(runner, f"SELECT geometric_mean(x) FROM {empty}") is None
    # NULL rows are ignored; pairwise masking for two-arg aggregates
    withnull = "(VALUES (1.0), (NULL), (3.0)) t(x)"
    assert _one(runner, f"SELECT var_pop(x) FROM {withnull}") == 1.0
    pairnull = "(VALUES (1.0, 2.0), (NULL, 5.0), (2.0, NULL), (3.0, 4.0)) t(y, x)"
    want = float(
        ((np.array([1.0, 3.0]) - 2.0) * (np.array([2.0, 4.0]) - 3.0)).mean()
    )
    assert _one(runner, f"SELECT covar_pop(y, x) FROM {pairnull}") == pytest.approx(
        want
    )


def test_approx_distinct(runner):
    got = _one(runner, "SELECT approx_distinct(l_suppkey) FROM lineitem")
    assert got == 100
    got = _one(
        runner,
        "SELECT approx_distinct(o_custkey) FROM orders",
    )
    exact = _one(runner, "SELECT count(DISTINCT o_custkey) FROM orders")
    assert got == exact


def test_grouped_composite(runner):
    """Grouped finisher projection + oracle per group."""
    rows = runner.execute(
        "SELECT l_returnflag, var_samp(l_quantity), count_if(l_quantity > 25)"
        " FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
    ).rows
    data = runner.execute("SELECT l_returnflag, l_quantity FROM lineitem").rows
    by_flag = {}
    for f, q in data:
        by_flag.setdefault(f, []).append(q)
    assert len(rows) == len(by_flag)
    for flag, var, cnt in rows:
        qs = np.array(by_flag[flag], dtype=float)
        assert var == pytest.approx(float(np.var(qs, ddof=1)), rel=1e-9)
        assert cnt == int((qs > 25).sum())


def test_dedup_with_grouping_sets(runner):
    """Two textually-distinct but structurally-identical aggregates
    dedup to one accumulator; the finisher projection must still emit
    one channel per call (ROLLUP expansion indexes per call)."""
    rows = runner.execute(
        "SELECT g, sum(x), sum(t.x) FROM"
        " (VALUES (1, 1.0), (1, 2.0), (2, 3.0)) t(g, x)"
        " GROUP BY ROLLUP(g) ORDER BY g"
    ).rows
    assert rows == [[1, 3.0, 3.0], [2, 3.0, 3.0], [None, 6.0, 6.0]]


def test_composite_shares_primitives(runner):
    """corr + covar + stddev over the same columns dedup their moment
    primitives; results must still all be correct."""
    got = runner.execute(
        f"SELECT corr(y, x), covar_pop(y, x), var_pop(x), avg(x) FROM {PAIRS}"
    ).rows[0]
    want = [
        float(np.corrcoef(YS, XS)[0, 1]),
        float(((YS - YS.mean()) * (XS - XS.mean())).mean()),
        float(np.var(XS)),
        float(XS.mean()),
    ]
    for g, w in zip(got, want):
        assert g == pytest.approx(w, rel=1e-9)
