"""Composite aggregate library: variance/covariance/moment family,
boolean/conditional aggregates, approx_distinct — lowered by the
analyzer onto shared sum/count/min/max primitives plus a finisher
projection (the accumulator-state analogue of Trino's
main/operator/aggregation/ library, e.g. VarianceState; SURVEY.md
§2.6 "Aggregation functions")."""

import math

import numpy as np
import pytest



@pytest.fixture(scope="module")
def runner(tpch_local):
    return tpch_local


V = "(VALUES (1.0), (2.0), (3.0), (4.0), (10.0)) t(x)"
X = np.array([1.0, 2.0, 3.0, 4.0, 10.0])

PAIRS = "(VALUES (1.0, 2.0), (2.0, 3.5), (3.0, 3.0), (4.0, 8.0)) t(y, x)"
YS = np.array([1.0, 2.0, 3.0, 4.0])
XS = np.array([2.0, 3.5, 3.0, 8.0])


def _one(runner, sql):
    return runner.execute(sql).only_value()


CASES = [
    (f"SELECT var_samp(x) FROM {V}", np.var(X, ddof=1)),
    (f"SELECT var_pop(x) FROM {V}", np.var(X)),
    (f"SELECT variance(x) FROM {V}", np.var(X, ddof=1)),
    (f"SELECT stddev_samp(x) FROM {V}", np.std(X, ddof=1)),
    (f"SELECT stddev_pop(x) FROM {V}", np.std(X)),
    (f"SELECT stddev(x) FROM {V}", np.std(X, ddof=1)),
    (f"SELECT geometric_mean(x) FROM {V}", float(np.exp(np.mean(np.log(X))))),
    (f"SELECT covar_samp(y, x) FROM {PAIRS}", float(np.cov(YS, XS, ddof=1)[0, 1])),
    (
        f"SELECT covar_pop(y, x) FROM {PAIRS}",
        float(((YS - YS.mean()) * (XS - XS.mean())).mean()),
    ),
    (f"SELECT corr(y, x) FROM {PAIRS}", float(np.corrcoef(YS, XS)[0, 1])),
    (
        f"SELECT regr_slope(y, x) FROM {PAIRS}",
        float(np.cov(YS, XS, ddof=1)[0, 1] / np.var(XS, ddof=1)),
    ),
    (
        f"SELECT regr_intercept(y, x) FROM {PAIRS}",
        float(
            YS.mean()
            - (np.cov(YS, XS, ddof=1)[0, 1] / np.var(XS, ddof=1)) * XS.mean()
        ),
    ),
]


@pytest.mark.parametrize("sql,want", CASES)
def test_numeric_aggregate(sql, want, runner):
    got = _one(runner, sql)
    assert got == pytest.approx(want, rel=1e-9, abs=1e-9), sql


def test_moments(runner):
    n = len(X)
    m = X.mean()
    m2 = ((X - m) ** 2).sum()
    m3 = ((X - m) ** 3).sum()
    m4 = ((X - m) ** 4).sum()
    skew = math.sqrt(n) * m3 / m2**1.5
    kurt = (
        n * (n + 1) * (n - 1) / ((n - 2) * (n - 3)) * m4 / m2**2
        - 3 * (n - 1) ** 2 / ((n - 2) * (n - 3))
    )
    assert _one(runner, f"SELECT skewness(x) FROM {V}") == pytest.approx(skew)
    assert _one(runner, f"SELECT kurtosis(x) FROM {V}") == pytest.approx(kurt)


def test_boolean_and_conditional_aggregates(runner):
    b = "(VALUES (true), (false), (NULL), (true)) t(b)"
    assert _one(runner, f"SELECT bool_and(b) FROM {b}") is False
    assert _one(runner, f"SELECT bool_or(b) FROM {b}") is True
    assert _one(runner, f"SELECT every(b) FROM {b}") is False
    assert _one(runner, f"SELECT count_if(b) FROM {b}") == 2
    t = "(VALUES (true), (true)) t(b)"
    assert _one(runner, f"SELECT bool_and(b) FROM {t}") is True
    # count_if over an empty relation is 0, not NULL
    assert (
        _one(runner, "SELECT count_if(b) FROM (VALUES (true)) t(b) WHERE b = false")
        == 0
    )


def test_null_and_small_n_semantics(runner):
    one = "(VALUES (42.0)) t(x)"
    assert _one(runner, f"SELECT var_samp(x) FROM {one}") is None
    assert _one(runner, f"SELECT var_pop(x) FROM {one}") == 0.0
    assert _one(runner, f"SELECT stddev_samp(x) FROM {one}") is None
    empty = "(VALUES (1.0)) t(x) WHERE x < 0"
    assert _one(runner, f"SELECT var_pop(x) FROM {empty}") is None
    assert _one(runner, f"SELECT geometric_mean(x) FROM {empty}") is None
    # NULL rows are ignored; pairwise masking for two-arg aggregates
    withnull = "(VALUES (1.0), (NULL), (3.0)) t(x)"
    assert _one(runner, f"SELECT var_pop(x) FROM {withnull}") == 1.0
    pairnull = "(VALUES (1.0, 2.0), (NULL, 5.0), (2.0, NULL), (3.0, 4.0)) t(y, x)"
    want = float(
        ((np.array([1.0, 3.0]) - 2.0) * (np.array([2.0, 4.0]) - 3.0)).mean()
    )
    assert _one(runner, f"SELECT covar_pop(y, x) FROM {pairnull}") == pytest.approx(
        want
    )


def test_approx_distinct(runner):
    # r3: approx_distinct is a real mergeable HLL sketch (2048 registers,
    # 2.3% standard error) rather than the old exact holistic gather —
    # assert within 3 sigma of truth, like the reference's tests
    got = _one(runner, "SELECT approx_distinct(l_suppkey) FROM lineitem")
    assert abs(got - 100) <= 7
    got = _one(
        runner,
        "SELECT approx_distinct(o_custkey) FROM orders",
    )
    exact = _one(runner, "SELECT count(DISTINCT o_custkey) FROM orders")
    assert abs(got - exact) / exact < 0.07


def test_grouped_composite(runner):
    """Grouped finisher projection + oracle per group."""
    rows = runner.execute(
        "SELECT l_returnflag, var_samp(l_quantity), count_if(l_quantity > 25)"
        " FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
    ).rows
    data = runner.execute("SELECT l_returnflag, l_quantity FROM lineitem").rows
    by_flag = {}
    for f, q in data:
        by_flag.setdefault(f, []).append(q)
    assert len(rows) == len(by_flag)
    for flag, var, cnt in rows:
        qs = np.array(by_flag[flag], dtype=float)
        assert var == pytest.approx(float(np.var(qs, ddof=1)), rel=1e-9)
        assert cnt == int((qs > 25).sum())


def test_dedup_with_grouping_sets(runner):
    """Two textually-distinct but structurally-identical aggregates
    dedup to one accumulator; the finisher projection must still emit
    one channel per call (ROLLUP expansion indexes per call)."""
    rows = runner.execute(
        "SELECT g, sum(x), sum(t.x) FROM"
        " (VALUES (1, 1.0), (1, 2.0), (2, 3.0)) t(g, x)"
        " GROUP BY ROLLUP(g) ORDER BY g"
    ).rows
    assert rows == [[1, 3.0, 3.0], [2, 3.0, 3.0], [None, 6.0, 6.0]]


def test_composite_shares_primitives(runner):
    """corr + covar + stddev over the same columns dedup their moment
    primitives; results must still all be correct."""
    got = runner.execute(
        f"SELECT corr(y, x), covar_pop(y, x), var_pop(x), avg(x) FROM {PAIRS}"
    ).rows[0]
    want = [
        float(np.corrcoef(YS, XS)[0, 1]),
        float(((YS - YS.mean()) * (XS - XS.mean())).mean()),
        float(np.var(XS)),
        # Trino: avg(decimal(2,1)) -> decimal(2,1), so 4.125 rounds
        # half-away to the argument scale
        round(float(XS.mean()), 1),
    ]
    for g, w in zip(got, want):
        assert g == pytest.approx(w, rel=1e-9)


class TestApproxDistinct:
    """approx_distinct on the holistic path (VERDICT r1 #9): exact
    distinct counts (error 0 satisfies the approximate contract),
    MIXABLE with other aggregates, correct distributed."""

    MIXED_Q = (
        "SELECT l_returnflag, approx_distinct(l_suppkey), count(*),"
        " sum(l_quantity), approx_distinct(l_shipmode)"
        " FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
    )

    def test_mixed_with_other_aggregates(self, runner):
        rows = runner.execute(self.MIXED_Q).rows
        assert len(rows) == 3
        check = runner.execute(
            "SELECT count(distinct l_suppkey) FROM lineitem"
            " WHERE l_returnflag = 'A'"
        ).only_value()
        # r4 un-gated the mergeable HLL rewrite for mixed aggregate
        # sets (VERDICT r3 item #3), so the result is approximate:
        # 2048 registers, 3 sigma of the 2.3% standard error
        assert abs(rows[0][1] - check) <= max(3 * 0.023 * check, 1)

    def test_distributed_matches_local(self, runner, tpch_cluster):
        d = tpch_cluster
        assert d.execute(self.MIXED_Q).rows == runner.execute(self.MIXED_Q).rows
        # approx_percentile distributed rides the same gathered path
        pq = (
            "SELECT l_returnflag, approx_percentile(l_quantity, 0.5)"
            " FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
        )
        assert d.execute(pq).rows == runner.execute(pq).rows

    def test_nulls_excluded(self, runner):
        got = runner.execute(
            "SELECT approx_distinct(nullif(l_linenumber, 1)) FROM lineitem"
        ).only_value()
        want = runner.execute(
            "SELECT count(distinct l_linenumber) FROM lineitem"
        ).only_value()
        assert got == want - 1


class TestHolisticAggregates:
    """min_by / max_by / approx_percentile — order-statistic aggregates
    on the collect path (exec/operators._finish_holistic; the planner
    forces single-step, SURVEY.md §2.6 aggregation functions)."""

    def test_min_max_by_global(self, runner):
        rows = runner.execute(
            "SELECT max_by(n_name, n_nationkey), min_by(n_name, n_nationkey)"
            " FROM nation"
        ).rows
        data = runner.execute("SELECT n_name, n_nationkey FROM nation").rows
        assert rows[0][0] == max(data, key=lambda r: r[1])[0]
        assert rows[0][1] == min(data, key=lambda r: r[1])[0]

    def test_min_max_by_grouped_oracle(self, runner):
        rows = runner.execute(
            "SELECT l_returnflag, max_by(l_orderkey, l_extendedprice),"
            " min_by(l_orderkey, l_extendedprice)"
            " FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
        ).rows
        data = runner.execute(
            "SELECT l_returnflag, l_orderkey, l_extendedprice FROM lineitem"
        ).rows
        by_flag = {}
        for f, ok, price in data:
            by_flag.setdefault(f, []).append((ok, price))
        for flag, got_max, got_min in rows:
            prices = by_flag[flag]
            best = max(p for _, p in prices)
            worst = min(p for _, p in prices)
            assert got_max in [ok for ok, p in prices if p == best]
            assert got_min in [ok for ok, p in prices if p == worst]

    def test_min_by_ignores_null_ordering_rows(self, runner):
        got = runner.execute(
            "SELECT max_by(x, y) FROM (VALUES (1, 10), (2, NULL), (3, 5)) t(x, y)"
        ).only_value()
        assert got == 1
        # all-NULL ordering column -> NULL
        assert runner.execute(
            "SELECT max_by(x, y) FROM (VALUES (1, NULL)) t(x, y)"
        ).only_value() is None

    def test_approx_percentile_oracle(self, runner):
        import numpy as np

        qs = np.array(
            [v[0] for v in runner.execute("SELECT l_quantity FROM lineitem").rows],
            dtype=float,
        )
        for p in (0.0, 0.25, 0.5, 0.9, 1.0):
            got = runner.execute(
                f"SELECT approx_percentile(l_quantity, {p}) FROM lineitem"
            ).only_value()
            want = float(np.sort(qs)[int(np.floor(p * (len(qs) - 1) + 0.5))])
            assert got == want, (p, got, want)

    def test_approx_percentile_grouped(self, runner):
        import numpy as np

        rows = runner.execute(
            "SELECT l_linestatus, approx_percentile(l_extendedprice, 0.5)"
            " FROM lineitem GROUP BY l_linestatus ORDER BY l_linestatus"
        ).rows
        data = runner.execute(
            "SELECT l_linestatus, l_extendedprice FROM lineitem"
        ).rows
        groups = {}
        for s, p in data:
            groups.setdefault(s, []).append(float(p))
        for status, got in rows:
            xs = np.sort(np.array(groups[status]))
            want = float(xs[int(np.floor(0.5 * (len(xs) - 1) + 0.5))])
            # r3: approx_percentile is a mergeable quantile-bucket sketch
            # (<= 1.6% relative bucket width, sql/optimizer
            # RewriteApproxPercentile) — assert the documented bound
            assert got == pytest.approx(want, rel=0.016), status

    def test_mixed_with_regular_aggregates(self, runner):
        rows = runner.execute(
            "SELECT n_regionkey, count(*), max_by(n_name, n_nationkey),"
            " sum(n_nationkey) FROM nation GROUP BY n_regionkey"
            " ORDER BY n_regionkey"
        ).rows
        data = runner.execute(
            "SELECT n_regionkey, n_nationkey, n_name FROM nation"
        ).rows
        by_rk = {}
        for rk, nk, nm in data:
            by_rk.setdefault(rk, []).append((nk, nm))
        for rk, cnt, mb, s in rows:
            assert cnt == len(by_rk[rk])
            assert s == sum(nk for nk, _ in by_rk[rk])
            assert mb == max(by_rk[rk])[1]

    def test_string_dictionary_preserved_through_min_max(self, runner):
        # regression: single-step min/max over a string column must keep
        # its dictionary (previously rendered raw codes)
        assert runner.execute(
            "SELECT min(n_name), max(n_name) FROM nation"
        ).rows == [["ALGERIA", "VIETNAM"]]

    def test_empty_input_semantics(self, runner):
        rows = runner.execute(
            "SELECT max_by(n_name, n_nationkey), approx_percentile(n_nationkey, 0.5),"
            " count(*) FROM nation WHERE n_nationkey < 0"
        ).rows
        assert rows == [[None, None, 0]]

    def test_distributed_forces_single_step(self, tpch_cluster):
        d = tpch_cluster
        rows = d.execute(
            "SELECT l_returnflag, approx_percentile(l_quantity, 0.5),"
            " max_by(l_orderkey, l_extendedprice)"
            " FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
        ).rows
        assert len(rows) == 3 and all(r[1] is not None for r in rows)

    def test_zero_batches_global(self, runner):
        # truly-empty input (LIMIT 0: no batches reach the operator)
        rows = runner.execute(
            "SELECT max_by(x, y), approx_percentile(y, 0.5), count(*) FROM"
            " (SELECT n_nationkey x, n_regionkey y FROM nation LIMIT 0) t"
        ).rows
        assert rows == [[None, None, 0]]

    def test_listagg_and_string_agg(self, runner):
        assert runner.execute(
            "SELECT listagg(r_name, ', ') FROM region"
        ).only_value() == "AFRICA, AMERICA, ASIA, EUROPE, MIDDLE EAST"
        rows = runner.execute(
            "SELECT n_regionkey, string_agg(n_name, '|') FROM nation"
            " WHERE n_nationkey < 6 GROUP BY n_regionkey ORDER BY n_regionkey"
        ).rows
        assert rows == [
            [0, "ALGERIA|ETHIOPIA"],
            [1, "ARGENTINA|BRAZIL|CANADA"],
            [4, "EGYPT"],
        ]
        # empty input -> NULL; non-string arg rejected
        assert runner.execute(
            "SELECT listagg(r_name, '-') FROM region WHERE r_regionkey < 0"
        ).only_value() is None
        from trino_tpu.sql.analyzer import AnalysisError

        with pytest.raises(AnalysisError):
            runner.execute("SELECT listagg(r_regionkey, '-') FROM region")

    def test_listagg_downstream_expressions_fail_loudly(self, runner):
        """Plan-time string ops cannot know listagg's execution-time
        dictionary; they must raise cleanly, never return wrong rows."""
        for sql in (
            "SELECT k, s FROM (SELECT n_regionkey k, string_agg(n_name,'|') s"
            " FROM nation GROUP BY n_regionkey) t WHERE s = 'EGYPT'",
            "SELECT upper(s) FROM (SELECT listagg(r_name, '-') s FROM region) t",
        ):
            with pytest.raises(NotImplementedError):
                runner.execute(sql)
        from trino_tpu.sql.analyzer import AnalysisError

        with pytest.raises(AnalysisError):
            runner.execute("SELECT listagg(r_name, 7) FROM region")
