"""Parser tests (tier 1 — parser round-trips, SURVEY.md §4.1)."""

import pytest

from tests.tpch_queries import QUERIES
from trino_tpu.sql import ast
from trino_tpu.sql.parser import ParsingError, parse, parse_query


def test_simple_select():
    q = parse_query("SELECT a, b AS x FROM t WHERE a > 1")
    spec = q.body
    assert isinstance(spec, ast.QuerySpec)
    assert spec.select[0].expr == ast.Identifier(("a",))
    assert spec.select[1].alias == "x"
    assert isinstance(spec.from_, ast.TableRef)
    assert spec.from_.name == ("t",)
    assert isinstance(spec.where, ast.BinaryOp)
    assert spec.where.op == "gt"


def test_precedence():
    q = parse_query("SELECT 1 + 2 * 3 = 7 AND NOT a OR b")
    e = q.body.select[0].expr
    # ((1 + (2*3)) = 7 AND (NOT a)) OR b
    assert isinstance(e, ast.BinaryOp) and e.op == "or"
    land = e.left
    assert land.op == "and"
    cmp_ = land.left
    assert cmp_.op == "eq"
    add = cmp_.left
    assert add.op == "add"
    assert add.right.op == "mul"
    assert isinstance(land.right, ast.UnaryOp) and land.right.op == "not"


def test_between_in_like_is():
    q = parse_query(
        "SELECT * FROM t WHERE a BETWEEN 1 AND 2 AND b NOT IN (1, 2)"
        " AND c LIKE 'x%' ESCAPE '#' AND d IS NOT NULL AND e NOT LIKE 'y'"
    )
    w = q.body.where
    parts = []

    def flatten(e):
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            flatten(e.left)
            flatten(e.right)
        else:
            parts.append(e)

    flatten(w)
    assert isinstance(parts[0], ast.Between)
    assert isinstance(parts[1], ast.InList) and parts[1].negated
    assert isinstance(parts[2], ast.Like) and parts[2].escape is not None
    assert isinstance(parts[3], ast.IsNullPredicate) and parts[3].negated
    assert isinstance(parts[4], ast.Like) and parts[4].negated


def test_joins():
    q = parse_query(
        "SELECT * FROM a JOIN b ON a.x = b.x LEFT OUTER JOIN c USING (y), d"
    )
    rel = q.body.from_
    assert isinstance(rel, ast.Join) and rel.kind == "cross"
    inner = rel.left
    assert inner.kind == "left" and inner.using == ("y",)
    assert inner.left.kind == "inner"


def test_subqueries_and_case():
    q = parse_query(
        """
        SELECT CASE WHEN a > 0 THEN 'pos' ELSE 'neg' END,
               CASE b WHEN 1 THEN 'one' END
        FROM t
        WHERE EXISTS (SELECT 1 FROM u WHERE u.id = t.id)
          AND a IN (SELECT x FROM v)
          AND b > (SELECT avg(x) FROM v)
        """
    )
    c1 = q.body.select[0].expr
    assert isinstance(c1, ast.Case) and c1.operand is None and c1.default is not None
    c2 = q.body.select[1].expr
    assert c2.operand is not None and c2.default is None


def test_literals():
    q = parse_query(
        "SELECT date '1998-12-01' - interval '90' day, 1.5, .5e2, 'it''s', NULL, TRUE"
    )
    items = [i.expr for i in q.body.select]
    assert isinstance(items[0], ast.BinaryOp)
    assert isinstance(items[0].left, ast.DateLiteral)
    assert isinstance(items[0].right, ast.IntervalLiteral)
    assert items[0].right.unit == "day"
    assert items[1] == ast.NumberLiteral("1.5")
    assert items[3] == ast.StringLiteral("it's")
    assert isinstance(items[4], ast.NullLiteral)
    assert items[5] == ast.BooleanLiteral(True)


def test_cast_extract_functions():
    q = parse_query(
        "SELECT CAST(a AS decimal(12, 2)), extract(year from d),"
        " count(*), count(DISTINCT x), substring(s, 1, 2) FROM t"
    )
    items = [i.expr for i in q.body.select]
    assert items[0].target == ast.TypeName("decimal", (12, 2))
    assert items[1] == ast.Extract("year", ast.Identifier(("d",)))
    assert items[2] == ast.FunctionCall("count", (ast.Star(),))
    assert items[3].distinct
    assert items[4].name == "substring"


def test_group_order_limit():
    q = parse_query(
        "SELECT a, sum(b) FROM t GROUP BY a HAVING sum(b) > 10"
        " ORDER BY 2 DESC NULLS FIRST, a ASC LIMIT 5"
    )
    assert q.body.group_by == (ast.Identifier(("a",)),)
    assert q.body.having is not None
    assert q.limit == 5
    assert q.order_by[0].descending and q.order_by[0].nulls_first is True
    assert not q.order_by[1].descending


def test_with_and_union():
    q = parse_query(
        "WITH r (a, b) AS (SELECT 1, 2) SELECT * FROM r"
        " UNION ALL SELECT * FROM r UNION SELECT 3, 4"
    )
    assert q.with_[0].name == "r" and q.with_[0].column_names == ("a", "b")
    body = q.body
    assert isinstance(body, ast.SetOperation) and body.op == "union" and not body.all
    assert isinstance(body.left, ast.SetOperation) and body.left.all


def test_show_and_explain():
    assert isinstance(parse("SHOW TABLES FROM tpch.tiny"), ast.ShowTables)
    assert isinstance(parse("SHOW SCHEMAS"), ast.ShowSchemas)
    e = parse("EXPLAIN ANALYZE SELECT 1")
    assert isinstance(e, ast.ExplainStatement) and e.analyze


def test_errors():
    with pytest.raises(ParsingError):
        parse("SELECT FROM t")
    with pytest.raises(ParsingError):
        parse("SELECT a FROM t WHERE")
    with pytest.raises(ParsingError):
        parse("SELECT a b c FROM t")
    with pytest.raises(ParsingError):
        parse("SELECT cast(a AS notatype) FROM t")


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_queries_parse(qid):
    q = parse_query(QUERIES[qid])
    assert isinstance(q, ast.Query)
    assert isinstance(q.body, ast.QuerySpec)


class TestUnnestAndArrays:
    """UNNEST + constant arrays (main/operator/unnest/ surface;
    SURVEY.md §2.6 'Set ops / misc' row)."""

    @staticmethod
    def _runner():
        from trino_tpu.connectors.tpch import create_tpch_connector
        from trino_tpu.engine import LocalQueryRunner, Session

        r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
        r.register_catalog("tpch", create_tpch_connector())
        return r

    def test_basic_unnest(self):
        r = self._runner()
        assert r.execute(
            "SELECT * FROM UNNEST(ARRAY[1, 2, 3]) AS t(x)"
        ).rows == [[1], [2], [3]]

    def test_multi_array_zip_with_ordinality(self):
        r = self._runner()
        rows = r.execute(
            "SELECT x, y, o FROM UNNEST(ARRAY['a','b'], ARRAY[10,20,30])"
            " WITH ORDINALITY AS t(x, y, o)"
        ).rows
        assert rows == [["a", 10, 1], ["b", 20, 2], [None, 30, 3]]

    def test_sequence(self):
        r = self._runner()
        assert r.execute(
            "SELECT sum(x) FROM UNNEST(sequence(1, 100)) AS t(x)"
        ).only_value() == 5050
        assert r.execute(
            "SELECT count(*) FROM UNNEST(sequence(10, 1, -3)) AS t(x)"
        ).only_value() == 4

    def test_unnest_join(self):
        r = self._runner()
        rows = r.execute(
            "SELECT n_name FROM nation, UNNEST(ARRAY[0, 5]) AS u(k)"
            " WHERE n_nationkey = k ORDER BY n_name"
        ).rows
        assert rows == [["ALGERIA"], ["ETHIOPIA"]]

    def test_array_functions(self):
        r = self._runner()
        row = r.execute(
            "SELECT cardinality(ARRAY[1,2,3]), element_at(ARRAY[5,6], -1),"
            " element_at(ARRAY[5,6], 9), contains(ARRAY[1,2], 2),"
            " contains(ARRAY[1,NULL], 9), array_join(ARRAY[1,2,3], '-'),"
            " array_max(ARRAY[4,9,2]), array_min(ARRAY[4,9,2]),"
            " cardinality(sequence(1, 10))"
        ).rows[0]
        assert row == [3, 6, None, True, None, "1-2-3", 9, 2, 10]

    def test_empty_array(self):
        r = self._runner()
        assert r.execute(
            "SELECT count(*) FROM UNNEST(ARRAY[]) AS t(x)"
        ).only_value() == 0
        assert r.execute("SELECT cardinality(ARRAY[])").only_value() == 0

    def test_array_column_rejected_cleanly(self):
        from trino_tpu.sql.analyzer import AnalysisError

        r = self._runner()
        import pytest as _pytest

        with _pytest.raises(AnalysisError):
            r.execute("SELECT * FROM nation, UNNEST(n_name) AS u(x)")
        # r4: cardinality(varchar) became the HyperLogLog accessor
        # (sketches ride the varchar carrier); a non-digest string is
        # NULL per row rather than an analysis error
        rows = r.execute("SELECT cardinality(n_name) FROM nation").rows
        assert all(v[0] is None for v in rows)

    def test_array_review_regressions(self):
        from trino_tpu.sql.analyzer import AnalysisError

        r = self._runner()
        import pytest as _pytest

        # NULL probe -> NULL (three-valued logic)
        assert r.execute("SELECT contains(ARRAY[1,2], NULL)").only_value() is None
        # incompatible element types fail at analysis, not execution
        with _pytest.raises(AnalysisError):
            r.execute("SELECT * FROM UNNEST(ARRAY[1, 'a']) AS t(x)")
        with _pytest.raises(AnalysisError):
            r.execute("SELECT array_max(ARRAY[1, 'a'])")
        # boolean vs integer is a type mismatch, not a python equality
        with _pytest.raises(AnalysisError):
            r.execute("SELECT contains(ARRAY[0], false)")
        # step sign contradicting direction is an error, not empty
        with _pytest.raises(AnalysisError):
            r.execute("SELECT * FROM UNNEST(sequence(1, 100, -3)) AS t(x)")
