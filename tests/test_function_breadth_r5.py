"""r5 function breadth — VERDICT r4 item #6.

qdigest family (quantile parity vs Python statistics), split_to_map,
session pseudo-columns, format_datetime Joda tokens, and the catalog
row count (SHOW FUNCTIONS lists one row per genuinely-accepted
overload, the reference's unit — SystemFunctionBundle.java:351)."""

import statistics

import pytest

from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.connectors.memory import create_memory_connector


@pytest.fixture(scope="module")
def r():
    r = LocalQueryRunner(
        Session(catalog="memory", schema="t", user="alice")
    )
    r.register_catalog("memory", create_memory_connector())
    return r


class TestQdigest:
    @pytest.fixture(scope="class")
    def rq(self, r):
        import random

        random.seed(7)
        self_vals = [
            (i % 3, random.gauss(100, 25)) for i in range(600)
        ]
        r.execute("create table memory.t.qd (g bigint, x double)")
        r.execute(
            "insert into qd values "
            + ", ".join(f"({g},{x})" for g, x in self_vals)
        )
        return r, self_vals

    def test_value_at_quantile(self, rq):
        r, vals = rq
        rows = r.execute(
            "select g, value_at_quantile(qdigest_agg(x), 0.5) "
            "from qd group by g order by g"
        ).rows
        for g, med in rows:
            exp = statistics.median([x for gg, x in vals if gg == g])
            assert abs(med - exp) <= 3.0, (g, med, exp)

    def test_values_at_quantiles(self, rq):
        r, vals = rq
        rows = r.execute(
            "select g, values_at_quantiles(qdigest_agg(x), "
            "array[0.1, 0.5, 0.9]) from qd group by g order by g"
        ).rows
        for g, arr in rows:
            assert len(arr) == 3
            assert arr[0] <= arr[1] <= arr[2]
            exp = statistics.median([x for gg, x in vals if gg == g])
            assert abs(arr[1] - exp) <= 3.0

    def test_qdigest_bigint(self, rq):
        r, _ = rq
        (v,) = r.execute(
            "select value_at_quantile(qdigest_agg(g), 0.99) from qd"
        ).rows[0]
        assert v == 2.0

    def test_quantile_at_value(self, rq):
        r, vals = rq
        (q,) = r.execute(
            "select quantile_at_value(qdigest_agg(x), 100.0) from qd"
        ).rows[0]
        frac = sum(1 for _, x in vals if x <= 100.0) / len(vals)
        assert abs(q - frac) < 0.1


class TestSplitToMap:
    def test_basic(self, r):
        r.execute("create table memory.t.sm (txt varchar)")
        r.execute(
            "insert into sm values ('a=1,b=2'), ('k=v'), ('')"
        )
        rows = r.execute("select split_to_map(txt, ',', '=') from sm").rows
        assert rows[0][0] == {"a": "1", "b": "2"}
        assert rows[1][0] == {"k": "v"}
        assert rows[2][0] == {}

    def test_element_and_cardinality(self, r):
        rows = r.execute(
            "select element_at(split_to_map(txt, ',', '='), 'a'), "
            "cardinality(split_to_map(txt, ',', '=')) from sm order by 2"
        ).rows
        assert [x[1] for x in rows] == [0, 1, 2]


class TestSessionPseudoColumns:
    def test_current_catalog_schema_user(self, r):
        rows = r.execute(
            "select current_catalog, current_schema, current_user"
        ).rows
        assert rows == [["memory", "t", "alice"]]


class TestJodaTokens:
    def test_full_month_day_names(self, r):
        (v,) = r.execute(
            "select format_datetime(timestamp '2024-07-04 15:30:45', "
            "'EEEE, MMMM d yyyy')"
        ).rows[0]
        assert v == "Thursday, July 04 2024"

    def test_day_of_year_and_half_day(self, r):
        (v,) = r.execute(
            "select format_datetime(timestamp '2024-02-01 13:05:00', "
            "'DDD h a')"
        ).rows[0]
        assert v == "032 01 PM"

    def test_parse_full_month(self, r):
        (v,) = r.execute(
            "select parse_datetime('July 4, 2024', 'MMMM d, yyyy')"
        ).rows[0]
        import datetime as dt

        assert v == int(
            (dt.datetime(2024, 7, 4) - dt.datetime(1970, 1, 1))
            .total_seconds() * 1e6
        )

    def test_format_tstz_wall_clock(self, r):
        (v,) = r.execute(
            "select format_datetime(timestamp "
            "'2024-07-04 15:30:45 America/New_York', 'yyyy-MM-dd HH:mm')"
        ).rows[0]
        assert v == "2024-07-04 15:30"


class TestCatalogBreadth:
    def test_row_count_and_agg_rows(self, r):
        rows = r.execute("show functions").rows
        assert len(rows) >= 630, len(rows)
        aggs = [x for x in rows if str(x[3]).lower() == "aggregate"]
        assert len(aggs) >= 200, len(aggs)

    def test_generic_overload_types_listed(self, r):
        rows = r.execute("show functions").rows
        min_rows = [x for x in rows if x[0] == "min"]
        assert len(min_rows) >= 12
        sigs = " ".join(str(x[1]) for x in min_rows)
        assert "timestamp with time zone" in sigs
