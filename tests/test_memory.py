"""Memory pools, revocation, spill (SURVEY.md §5.4 — revocable memory +
spill-to-disk; results must be identical with and without spilling)."""

import pytest

from trino_tpu import types as T
from trino_tpu.block import RelBatch
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.exec.spill import FileSpiller
from trino_tpu.runtime.memory import (
    ExceededMemoryLimitError,
    MemoryContext,
    MemoryPool,
)


def test_pool_reserve_free():
    pool = MemoryPool(1000)
    assert pool.try_reserve(600)
    assert not pool.try_reserve(600)
    pool.free(600)
    assert pool.try_reserve(600)


def test_pool_limit_enforced():
    pool = MemoryPool(100)
    with pytest.raises(ExceededMemoryLimitError):
        pool.reserve(200)


def test_pool_revokes_largest_first():
    pool = MemoryPool(1000)
    revoked = []

    def make(name, bytes_):
        ctx = MemoryContext(pool)

        def revoke():
            revoked.append(name)
            ctx.set_bytes(0)
            ctx.set_revocable_bytes(0)

        ctx.set_revoker(revoke)
        ctx.set_bytes(bytes_)
        ctx.set_revocable_bytes(bytes_)
        return ctx

    make("small", 200)
    make("big", 700)
    # 100 free; reserving 400 must revoke "big" first and then fit
    pool.reserve(400)
    assert revoked == ["big"]


def test_spiller_roundtrip():
    sp = FileSpiller()
    b = RelBatch.from_pydict(
        [("a", T.BIGINT), ("s", T.VARCHAR)],
        {"a": [1, 2, 3], "s": ["x", "y", "x"]},
    )
    sp.spill(b)
    sp.spill(b)
    assert sp.batch_count == 2
    out = list(sp.unspill())
    assert len(out) == 2
    assert out[0].to_pylists() == b.to_pylists()
    sp.close()


@pytest.fixture(scope="module")
def baseline():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


AGG_Q = (
    "select l_orderkey, sum(l_quantity) q, count(*) c from lineitem"
    " group by l_orderkey order by q desc, l_orderkey limit 10"
)
SORT_Q = (
    "select l_orderkey, l_extendedprice from lineitem"
    " order by l_extendedprice desc, l_orderkey limit 20"
)


def test_aggregation_spills_and_matches(baseline):
    base = baseline.execute(AGG_Q).rows
    r = LocalQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            batch_rows=8192, memory_pool_bytes=256 * 1024,
        )
    )
    r.register_catalog("tpch", create_tpch_connector())
    assert r.execute(AGG_Q).rows == base


def test_sort_spills_and_matches(baseline):
    base = baseline.execute(SORT_Q).rows
    r = LocalQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            batch_rows=4096, memory_pool_bytes=256 * 1024,
        )
    )
    r.register_catalog("tpch", create_tpch_connector())
    assert r.execute(SORT_Q).rows == base


JOIN_QS = {
    "inner_agg": (
        "select o_orderpriority, count(*), sum(l_quantity) from orders,"
        " lineitem where o_orderkey = l_orderkey"
        " group by o_orderpriority order by o_orderpriority"
    ),
    "left": (
        "select c_custkey, o_orderkey from customer left join orders"
        " on c_custkey = o_custkey where c_custkey < 50"
        " order by c_custkey, o_orderkey"
    ),
    "semi": (
        "select count(*) from orders where o_orderkey in"
        " (select l_orderkey from lineitem where l_quantity > 48)"
    ),
    "anti": (
        "select count(*) from customer where c_custkey not in"
        " (select o_custkey from orders)"
    ),
}


@pytest.mark.parametrize("shape", sorted(JOIN_QS))
def test_grace_join_spills_and_matches(baseline, shape):
    """Join build sides spill under memory pressure (grace hash join:
    HashBuilderOperator.java:163-206 + PartitionedLookupSourceFactory):
    a small pool forces revocation mid-build; results must be exact."""
    sql = JOIN_QS[shape]
    base = baseline.execute(sql).rows
    r = LocalQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            batch_rows=4096, memory_pool_bytes=192 * 1024,
        )
    )
    r.register_catalog("tpch", create_tpch_connector())
    assert r.execute(sql).rows == base


def test_grace_join_revocation_mid_build(baseline):
    """Direct revocation protocol check: revoke the build sink while
    batches are accumulating and keep feeding — the partitioned spill
    must absorb pre- and post-revoke rows alike."""
    from trino_tpu import types as T
    from trino_tpu.block import RelBatch
    from trino_tpu.exec.operators import (
        HashBuildSink,
        JoinBridge,
        LookupJoinOperator,
    )

    bridge = JoinBridge()
    schema = [(T.BIGINT, None), (T.BIGINT, None)]
    sink = HashBuildSink(bridge, [0], schema)
    b1 = RelBatch.from_pydict(
        [("k", T.BIGINT), ("v", T.BIGINT)],
        {"k": [1, 2, 3, 4], "v": [10, 20, 30, 40]},
    )
    b2 = RelBatch.from_pydict(
        [("k", T.BIGINT), ("v", T.BIGINT)],
        {"k": [3, 5], "v": [33, 50]},
    )
    sink.add_input(b1)
    sink._revoke_memory()  # mid-build revocation
    sink.add_input(b2)
    sink.finish()
    assert bridge.grace is not None and bridge.lookup_source is None

    probe = RelBatch.from_pydict(
        [("pk", T.BIGINT)], {"pk": [2, 3, 6]}
    )
    join = LookupJoinOperator(bridge, [0], "inner", [(T.BIGINT, None)])
    join.add_input(probe)
    join.finish()
    rows = []
    while True:
        out = join.get_output()
        if out is None:
            break
        rows.extend(out.to_pylists())
    assert sorted(rows) == [[2, 2, 20], [3, 3, 30], [3, 3, 33]]
