"""Node selection, bin-packed memory placement, FTE speculation
(runtime/node_scheduler.py + fte.py — NodeScheduler/UniformNodeSelector,
BinPackingNodeAllocatorService, PartitionMemoryEstimator, speculative
execution analogues)."""

import time

import pytest

from trino_tpu.runtime.node_scheduler import (
    BinPackingNodeAllocator,
    PartitionMemoryEstimator,
    UniformNodeSelector,
)


class _Node:
    def __init__(self, name, tasks=0, pool_bytes=None):
        self.name = name
        self._tasks = tasks
        if pool_bytes is not None:
            class _Pool:
                total_bytes = pool_bytes
            self.memory_pool = _Pool()

    def status(self):
        return {"tasks": self._tasks}


def test_uniform_selector_balances():
    nodes = [_Node("a"), _Node("b"), _Node("c")]
    sel = UniformNodeSelector()
    picks = [sel.select(nodes).name for _ in range(6)]
    # least-loaded first, ledger-tracked: even spread
    assert sorted(picks) == ["a", "a", "b", "b", "c", "c"]


def test_uniform_selector_cap_skips_busy():
    busy = _Node("busy", tasks=5)
    idle = _Node("idle", tasks=0)
    sel = UniformNodeSelector(max_tasks_per_node=3)
    assert sel.select([busy, idle]).name == "idle"


def test_uniform_selector_all_at_cap_falls_back():
    a = _Node("a", tasks=9)
    b = _Node("b", tasks=7)
    sel = UniformNodeSelector(max_tasks_per_node=3)
    assert sel.select([a, b]).name == "b"  # least-loaded overall


def test_uniform_selector_prefers_locality():
    a, b = _Node("a"), _Node("b")
    sel = UniformNodeSelector()
    assert sel.select([a, b], preferred=[b]).name == "b"


def test_uniform_selector_release():
    a, b = _Node("a"), _Node("b")
    sel = UniformNodeSelector()
    h = sel.select([a, b])
    sel.release(h)
    # after release the same node is the least loaded again
    assert sel.select([a, b]).name == h.name


class _FakeNodeManager:
    """schedulable_workers() protocol double (runtime/discovery.py)."""

    def __init__(self, ok):
        self._ok = ok

    def schedulable_workers(self):
        return list(self._ok)


def test_uniform_selector_skips_graylisted():
    a, b = _Node("a"), _Node("b")
    sel = UniformNodeSelector(node_manager=_FakeNodeManager([b]))
    # a's breaker is open: every pick lands on b, even as "preferred"
    assert all(sel.select([a, b]).name == "b" for _ in range(3))
    assert sel.select([a, b], preferred=[a]).name == "b"


def test_uniform_selector_all_gray_degrades():
    a, b = _Node("a"), _Node("b")
    sel = UniformNodeSelector(node_manager=_FakeNodeManager([]))
    # every breaker open: degrade to the full set rather than starve
    assert sel.select([a, b]).name in ("a", "b")


def test_bin_packing_skips_graylisted():
    small = _Node("small", pool_bytes=100)
    big = _Node("big", pool_bytes=1000)
    alloc = BinPackingNodeAllocator(
        node_manager=_FakeNodeManager([small])
    )
    # big has more room but its breaker is open
    assert alloc.acquire([small, big], estimated_bytes=10).name == "small"


def test_bin_packing_picks_most_free():
    small = _Node("small", pool_bytes=100)
    big = _Node("big", pool_bytes=1000)
    alloc = BinPackingNodeAllocator()
    assert alloc.acquire([small, big], 50).name == "big"
    # 950 free on big still beats 100 on small
    assert alloc.acquire([small, big], 50).name == "big"


def test_bin_packing_respects_fit():
    a = _Node("a", pool_bytes=100)
    b = _Node("b", pool_bytes=100)
    alloc = BinPackingNodeAllocator()
    h1 = alloc.acquire([a, b], 80)
    h2 = alloc.acquire([a, b], 80)  # only the other node still fits
    assert {h1.name, h2.name} == {"a", "b"}


def test_bin_packing_over_admits_when_full():
    a = _Node("a", pool_bytes=10)
    alloc = BinPackingNodeAllocator()
    alloc.acquire([a], 8)
    # nothing fits; still places (workers spill under pressure)
    assert alloc.acquire([a], 8).name == "a"


def test_bin_packing_release():
    a = _Node("a", pool_bytes=100)
    alloc = BinPackingNodeAllocator()
    alloc.acquire([a], 60)
    alloc.release(a, 60)
    assert alloc.free_bytes(a) == 100


def test_memory_estimator_grows_on_memory_failure():
    est = PartitionMemoryEstimator(default_bytes=100)
    assert est.estimate(0) == 100
    est.register_failure(0, "ExceededMemoryLimitError: query over budget")
    assert est.estimate(0) == 200
    est.register_failure(0, "worker unreachable")  # not memory-classed
    assert est.estimate(0) == 200


# -- FTE speculation end to end --


@pytest.fixture()
def fte_cluster():
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime.coordinator import DistributedQueryRunner
    from trino_tpu.runtime.failure import FailureInjector
    from trino_tpu.runtime.worker import Worker

    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [Worker(f"w{i}", cats, failure_injector=inj) for i in range(2)]
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="task"),
        worker_handles=workers,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r, inj


SPEC_QUERY = (
    "select o_orderstatus, count(*) c from orders"
    " group by o_orderstatus order by 1"
)


def test_fte_speculation_beats_straggler(fte_cluster):
    r, inj = fte_cluster
    baseline = r.execute(SPEC_QUERY).rows
    # one partition of the source stage (fragment 0, one task per
    # worker) stalls 30s on its first attempt; the speculative duplicate
    # (attempt 1) must finish the stage long before the stall expires
    inj.clear()
    inj.inject(
        fragment_id=0, partition=0, attempts=(0,), where="start",
        stall_s=30.0, max_hits=1,
    )
    t0 = time.monotonic()
    rows = r.execute(SPEC_QUERY).rows
    wall = time.monotonic() - t0
    assert rows == baseline
    assert wall < 25.0, f"speculation did not engage (wall {wall:.1f}s)"
