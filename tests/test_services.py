"""Client & service tier: DB-API driver, web UI / stats REST, proxy,
verifier (SURVEY.md §2.11: trino-jdbc, Web UI, trino-proxy,
trino-verifier)."""

import json
import urllib.request

import pytest

from trino_tpu import dbapi
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime.server import CoordinatorServer


@pytest.fixture(scope="module")
def server():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    srv = CoordinatorServer(r)
    yield srv
    srv.stop()


class TestDbapi:
    def test_basic_query(self, server):
        conn = dbapi.connect(server.uri, user="tester")
        cur = conn.cursor()
        cur.execute("SELECT n_nationkey, n_name FROM nation ORDER BY n_nationkey")
        assert cur.rowcount == 25
        assert [d[0] for d in cur.description] == ["n_nationkey", "n_name"]
        first = cur.fetchone()
        assert first == [0, "ALGERIA"]
        rest = cur.fetchall()
        assert len(rest) == 24
        assert cur.fetchone() is None

    def test_qmark_binding(self, server):
        conn = dbapi.connect(server.uri)
        cur = conn.cursor()
        cur.execute(
            "SELECT n_name FROM nation WHERE n_nationkey = ? AND n_name <> ?",
            (3, "it's"),
        )
        assert cur.fetchall() == [["CANADA"]]

    def test_qmark_skips_string_literals(self, server):
        cur = dbapi.connect(server.uri).cursor()
        cur.execute("SELECT 'a?b', ?", (7,))
        assert cur.fetchall() == [["a?b", 7]]

    def test_param_types(self, server):
        import datetime

        cur = dbapi.connect(server.uri).cursor()
        cur.execute(
            "SELECT ?, ?, ?, ?",
            (1.5, True, None, datetime.date(1995, 3, 15)),
        )
        row = cur.fetchall()[0]
        assert row[0] == 1.5 and row[1] is True and row[2] is None

    def test_error_surfaces(self, server):
        cur = dbapi.connect(server.uri).cursor()
        with pytest.raises(dbapi.DatabaseError):
            cur.execute("SELECT * FROM no_such_table")

    def test_iteration_and_fetchmany(self, server):
        cur = dbapi.connect(server.uri).cursor()
        cur.execute("SELECT r_name FROM region ORDER BY r_name")
        assert len(cur.fetchmany(2)) == 2
        assert len(list(cur)) == 3

    def test_transactions_via_dbapi(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="s"))
        r.register_catalog("memory", create_memory_connector())
        srv = CoordinatorServer(r)
        try:
            dbapi.connect(srv.uri).cursor().execute("CREATE TABLE t (x bigint)")
            conn = dbapi.connect(srv.uri, autocommit=False)
            cur = conn.cursor()
            cur.execute("INSERT INTO t VALUES (1)")
            conn.rollback()
            cur2 = dbapi.connect(srv.uri).cursor()
            cur2.execute("SELECT count(*) FROM t")
            assert cur2.fetchall() == [[0]]
            cur.execute("INSERT INTO t VALUES (2)")
            conn.commit()
            cur2.execute("SELECT count(*) FROM t")
            assert cur2.fetchall() == [[1]]
        finally:
            srv.stop()


class TestUiAndStats:
    def test_cluster_stats_and_query_list(self, server):
        dbapi.connect(server.uri).cursor().execute("SELECT 1")
        stats = json.load(
            urllib.request.urlopen(server.uri + "/v1/cluster", timeout=10)
        )
        assert stats["total_queries"] >= 1
        queries = json.load(
            urllib.request.urlopen(server.uri + "/v1/query", timeout=10)
        )
        assert any(q["sql"] == "SELECT 1" for q in queries)

    def test_ui_page(self, server):
        html = urllib.request.urlopen(server.uri + "/ui", timeout=10).read()
        assert b"trino-tpu coordinator" in html


class TestProxy:
    def test_round_robin_and_sticky_polling(self, server):
        from trino_tpu.service.proxy import ProxyServer

        proxy = ProxyServer([server.uri, server.uri])
        try:
            cur = dbapi.connect(proxy.uri).cursor()
            cur.execute("SELECT count(*) FROM lineitem")
            assert cur.fetchall() == [[60064]]
            # UI stats route through too
            stats = json.load(
                urllib.request.urlopen(proxy.uri + "/v1/cluster", timeout=10)
            )
            assert stats["total_queries"] >= 1
        finally:
            proxy.stop()


class TestVerifier:
    def test_match_and_mismatch(self, server):
        from trino_tpu.client import Client
        from trino_tpu.service.verifier import (
            Verifier, client_target, runner_target,
        )

        control = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
        control.register_catalog("tpch", create_tpch_connector())
        v = Verifier(
            runner_target(control), client_target(Client(server.uri))
        )
        results = v.verify_suite(
            {
                "counts": "SELECT n_regionkey, count(*) FROM nation GROUP BY n_regionkey",
                "ordered": "SELECT r_name FROM region ORDER BY r_name",
            }
        )
        assert all(r.status == "match" for r in results), results

        # a genuinely different answer must be flagged
        lying = Verifier(
            runner_target(control),
            lambda sql: [[999]],
        )
        r = lying.verify("x", "SELECT count(*) FROM region")
        assert r.status == "mismatch" and r.detail

    def test_error_classification(self):
        from trino_tpu.service.verifier import Verifier

        v = Verifier(lambda sql: [[1]], lambda sql: 1 / 0)
        assert v.verify("e", "SELECT 1").status == "test_error"
        v2 = Verifier(lambda sql: 1 / 0, lambda sql: [[1]])
        assert v2.verify("e", "SELECT 1").status == "control_error"


class TestReviewRegressions:
    def test_cross_connection_transaction_isolation(self):
        """Two HTTP connections must not share transaction state (the
        protocol threads X-Trino-Transaction-Id per connection)."""
        r = LocalQueryRunner(Session(catalog="memory", schema="s"))
        r.register_catalog("memory", create_memory_connector())
        srv = CoordinatorServer(r)
        try:
            dbapi.connect(srv.uri).cursor().execute("CREATE TABLE t (x bigint)")
            a = dbapi.connect(srv.uri, autocommit=False)
            b = dbapi.connect(srv.uri)  # autocommit
            a.cursor().execute("INSERT INTO t VALUES (1)")  # staged in A's txn
            b.cursor().execute("INSERT INTO t VALUES (2)")  # autocommit NOW
            check = dbapi.connect(srv.uri).cursor()
            check.execute("SELECT count(*) FROM t")
            assert check.fetchall() == [[1]]  # only B's row is visible
            a.rollback()
            check.execute("SELECT count(*) FROM t")
            assert check.fetchall() == [[1]]  # A's row discarded, B's kept
        finally:
            srv.stop()

    def test_dbapi_question_mark_in_comment(self, server):
        cur = dbapi.connect(server.uri).cursor()
        cur.execute("SELECT ? -- really?\n", (5,))
        assert cur.fetchall() == [[5]]
        cur.execute("SELECT ? /* hm? */", (6,))
        assert cur.fetchall() == [[6]]

    def test_proxy_preserves_content_type(self, server):
        from trino_tpu.service.proxy import ProxyServer

        proxy = ProxyServer([server.uri])
        try:
            resp = urllib.request.urlopen(proxy.uri + "/ui", timeout=10)
            assert "text/html" in resp.headers.get("Content-Type", "")
            assert b"trino-tpu coordinator" in resp.read()
        finally:
            proxy.stop()

    def test_verifier_subquery_order_by_not_ordered(self):
        from trino_tpu.service.verifier import _has_top_level_order_by

        assert _has_top_level_order_by("SELECT a FROM t ORDER BY a")
        assert not _has_top_level_order_by(
            "SELECT count(*) FROM (SELECT x FROM t ORDER BY x LIMIT 3) q"
        )

    def test_read_only_blocks_ddl(self):
        from trino_tpu.transaction import TransactionError

        r = LocalQueryRunner(Session(catalog="memory", schema="s"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("START TRANSACTION READ ONLY")
        import pytest as _pytest

        with _pytest.raises(TransactionError):
            r.execute("CREATE TABLE nope (x bigint)")
        with _pytest.raises(TransactionError):
            r.execute("CREATE TABLE nope AS SELECT 1")
        r.execute("ROLLBACK")
        # neither DDL left anything behind
        assert r.execute("SHOW TABLES").rows == []

    def test_distributed_runner_transactions(self):
        from trino_tpu.runtime.coordinator import DistributedQueryRunner

        d = DistributedQueryRunner(
            Session(catalog="memory", schema="s"), n_workers=2
        )
        d.register_catalog("memory", create_memory_connector())
        d.execute("CREATE TABLE t (x bigint)")
        d.execute("START TRANSACTION")
        d.execute("INSERT INTO t VALUES (1)")
        d.execute("ROLLBACK")
        assert d.execute("SELECT count(*) FROM t").only_value() == 0
        d.execute("INSERT INTO t VALUES (2)")
        assert d.execute("SELECT count(*) FROM t").only_value() == 1
