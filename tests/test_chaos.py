"""Seeded chaos tests for the cluster resiliency layer (PR2 tentpole).

Fast tier-1 matrix: two representative TPC-H-shaped queries under every
fault class (task crash at start/mid, exchange fetch loss, straggler,
injected OOM) with a FIXED seed, asserting oracle-equal results and
bounded attempt counts. The full 22-query soak carries
@pytest.mark.slow. Graylist and low-memory-killer semantics get their
own deterministic tests (no background heartbeat thread — the probe
loop is driven by explicit ping_once calls)."""

import threading
import time

import pytest

from tests.oracle import assert_rows_match, sqlite_rows
from tests.test_tpch import to_sqlite
from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import Session
from trino_tpu.runtime import DistributedQueryRunner, Worker
from trino_tpu.runtime.chaos import (
    FAULT_CLASSES,
    ChaosHarness,
    DownableWorker,
    generate_schedule,
)
from trino_tpu.runtime.failure import FailureInjector
from trino_tpu.runtime.memory import ExceededMemoryLimitError

SF = 0.01
SEED = 42

Q_AGG = (
    "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)
Q_JOIN = (
    "select n_name, count(*) c from supplier, nation "
    "where s_nationkey = n_nationkey "
    "group by n_name order by n_name"
)


@pytest.fixture(scope="module")
def oracle():
    import sqlite3

    from tests.oracle import load_tpch_sqlite

    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def harness():
    h = ChaosHarness(n_workers=2)
    h.register_catalog("tpch", create_tpch_connector())
    return h


# -- the seeded fault matrix ------------------------------------------------

@pytest.mark.parametrize("fault_class", FAULT_CLASSES)
@pytest.mark.parametrize("sql", [Q_AGG, Q_JOIN], ids=["agg", "join"])
def test_chaos_matrix(sql, fault_class, harness, oracle):
    rows, stats = harness.run_case(sql, fault_class, seed=SEED)
    expected = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    # attempts stay bounded by the schedule: every injected failure can
    # cause at most one retry (stalls cause speculation, not retries)
    assert stats["retries"] <= stats["max_injected_failures"], stats
    if fault_class == "fetch_loss":
        # transient fetch loss is absorbed by the exchange retry loop:
        # no task was ever re-run
        assert stats["retries"] == 0, stats


def test_schedule_determinism():
    for fc in FAULT_CLASSES:
        assert generate_schedule(SEED, fc) == generate_schedule(SEED, fc)
    assert generate_schedule(1, "task_crash_start") != generate_schedule(
        2, "task_crash_start"
    ) or True  # different seeds may collide on tiny schedules; the
    # invariant under test is same-seed stability above


@pytest.mark.slow
@pytest.mark.parametrize("fault_class", FAULT_CLASSES)
@pytest.mark.parametrize("qid", list(range(1, 23)))
def test_chaos_soak_tpch(qid, fault_class, harness, oracle):
    """The full soak: all 22 TPC-H queries under every fault class."""
    from tests.tpch_queries import QUERIES

    sql = QUERIES[qid]
    rows, stats = harness.run_case(sql, fault_class, seed=SEED + qid)
    expected = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(
        rows, expected, ordered=("order by" in sql), abs_tol=1e-2
    )
    assert stats["retries"] <= stats["max_injected_failures"]


# -- cluster lifecycle: graceful drain + speculation (PR 3) -----------------


def _lifecycle_harness(n: int = 3) -> ChaosHarness:
    """Drains are one-way (a drained node never rejoins), so every
    lifecycle test runs on a fresh harness."""
    h = ChaosHarness(n_workers=n)
    h.register_catalog("tpch", create_tpch_connector())
    return h


def test_drain_mid_query(oracle):
    """Gracefully draining a worker mid-query: the query completes with
    oracle-equal rows (no query-level failure, no duplicates), the
    drained worker accepts ZERO launches after the drain landed, and the
    node settles in the `drained` state."""
    h = _lifecycle_harness()
    rows, report = h.run_drain_case(Q_JOIN, seed=SEED)
    expected = sqlite_rows(oracle, to_sqlite(Q_JOIN))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    assert all(report["drained"].values()), report
    assert report["launches_at_end"] == report["launches_at_drain"], report
    for wid in report["drained"]:
        assert report["node_states"][wid] == "drained", report


def test_drain_all_but_one(oracle):
    """Draining every worker except one mid-query still converges: the
    survivor absorbs all remaining work."""
    h = _lifecycle_harness()
    rows, report = h.run_drain_case(
        Q_JOIN, seed=SEED, drain_all_but_one=True
    )
    expected = sqlite_rows(oracle, to_sqlite(Q_JOIN))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    assert len(report["drained"]) == 2
    assert all(report["drained"].values()), report
    assert report["launches_at_end"] == report["launches_at_drain"], report
    states = report["node_states"]
    assert sum(1 for s in states.values() if s == "active") == 1, states


def test_straggler_speculation_wins(oracle):
    """A hard-stalled first attempt loses to its speculative duplicate:
    the win is RECORDED (not just a duplicate launched), rows carry no
    duplicates, and attempts per partition stay bounded."""
    h = _lifecycle_harness()
    rows, stats = h.run_speculation_case(Q_AGG, seed=SEED)
    expected = sqlite_rows(oracle, to_sqlite(Q_AGG))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    assert stats["speculation_wins"] >= 1, stats
    # stalls cause speculation, not retries; at most one duplicate each
    assert max(stats["attempts_per_partition"].values()) <= 2, stats


def test_speculation_disabled_by_session_property():
    """speculation_enabled=false: the stalled attempt just runs long —
    no duplicate is ever launched."""
    session = Session(
        catalog="tpch", schema="tiny", retry_policy="task",
        speculation_enabled=False,
    )
    h = ChaosHarness(n_workers=2, session=session)
    h.register_catalog("tpch", create_tpch_connector())
    rows, stats = h.run_speculation_case(Q_JOIN, seed=SEED, stall_s=0.6)
    assert rows
    assert stats["speculative_hits"] == 0, stats


# -- QUERY-level retry (retry_policy=query) ---------------------------------


def _retry_cluster():
    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [
        Worker(f"qr-w{i}", cats, failure_injector=inj) for i in range(2)
    ]
    return inj, workers


def test_query_retry_recovers_where_task_retries_exhausted(oracle):
    """The acceptance fault: partition 0 of the scan dies on its first
    FOUR attempts. retry_policy=TASK exhausts its per-task budget and
    fails; retry_policy=QUERY absorbs the same fault by re-running the
    whole query (deterministic replay, fresh task namespace) and
    recovers."""
    from trino_tpu.runtime.fte import TaskRetriesExceeded

    inj, workers = _retry_cluster()
    fault = dict(
        where="start", fragment_id=0, partition=0,
        attempts=tuple(range(8)), max_hits=4,
    )

    r_task = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="task",
                task_retries=3),
        worker_handles=workers, hash_partitions=2,
    )
    r_task.register_catalog("tpch", create_tpch_connector())
    inj.inject(**fault)
    with pytest.raises(TaskRetriesExceeded):
        r_task.execute(Q_JOIN)
    inj.clear()

    r_query = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="query",
                query_retry_count=5),
        worker_handles=workers, hash_partitions=2,
    )
    r_query.register_catalog("tpch", create_tpch_connector())
    inj.inject(**fault)
    try:
        rows = r_query.execute(Q_JOIN).rows
    finally:
        inj.clear()
    expected = sqlite_rows(oracle, to_sqlite(Q_JOIN))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    # 4 failed whole-query attempts + the clean 5th
    assert r_query.last_query_attempts == 5


def test_query_retry_transparent_to_client_protocol():
    """An internal whole-query retry is invisible on the client
    statement protocol: one query id, nextUri polling just sees a
    longer run, the final page carries the right rows."""
    import json as _json
    import urllib.request

    inj, workers = _retry_cluster()
    runner = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="query",
                query_retry_count=2),
        worker_handles=workers, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())

    class _Front:
        """CoordinatorServer passes `prepared`; the distributed runner
        doesn't take it — adapt."""

        def execute(self, sql, identity=None, transaction_id=None,
                    prepared=None):
            return runner.execute(
                sql, identity=identity, transaction_id=transaction_id
            )

    from trino_tpu.runtime.server import CoordinatorServer

    inj.inject(where="start", fragment_id=0, partition=0,
               attempts=(0,), max_hits=1)
    srv = CoordinatorServer(_Front(), port=0)
    try:
        req = urllib.request.Request(
            srv.uri + "/v1/statement",
            data=b"select count(*) from nation", method="POST",
        )
        resp = _json.load(urllib.request.urlopen(req, timeout=10))
        qid = resp["id"]
        seen_ids = {qid}
        while "nextUri" in resp:
            resp = _json.load(
                urllib.request.urlopen(resp["nextUri"], timeout=10)
            )
            seen_ids.add(resp["id"])
        assert resp["stats"]["state"] == "FINISHED", resp
        assert resp["data"] == [[25]]
        assert seen_ids == {qid}
        assert runner.last_query_attempts == 2  # it DID retry internally
    finally:
        srv.stop()
        inj.clear()


# -- worker drain + kill over HTTP ------------------------------------------


def test_http_fail_query_endpoint_kills_running_query():
    """DELETE /v1/query/{id}?reason=... on the worker HTTP surface:
    every task of the query fails with the kill reason and the
    coordinator's poll surfaces it as the query-level error."""
    from trino_tpu.runtime.http import HttpWorkerClient, WorkerServer

    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    w = Worker("kill-w0", cats, failure_injector=inj)
    srv = WorkerServer(w, require_secret=False)
    try:
        handle = HttpWorkerClient(srv.uri)
        runner = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny"),
            worker_handles=[handle],
        )
        runner.register_catalog("tpch", create_tpch_connector())
        inj.inject(where="start", attempts=(0,), stall_s=5.0, max_hits=1)
        err = []

        def run():
            try:
                runner.execute("select count(*) from nation")
            except Exception as e:
                err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while not w.task_ids() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert w.task_ids(), "query never launched a task"
        qid = w.task_ids()[0].split(".")[0]
        handle.fail_query(qid, "killed by test")
        t.join(30)
        assert not t.is_alive()
        assert err, "kill should surface as a query-level failure"
        assert "killed by test" in str(err[0])
    finally:
        srv.stop()
        inj.clear()


def test_http_drain_via_state_api_excludes_worker():
    """PUT /v1/info/state "SHUTTING_DOWN" (the reference worker-state
    API) over HTTP: the worker reports shutting_down, the heartbeat
    settles it to drained, and new queries place zero tasks on it."""
    from trino_tpu.runtime.http import HttpWorkerClient, WorkerServer

    servers, handles, inner = [], [], []
    try:
        for i in range(2):
            cats = CatalogManager()
            cats.register("tpch", create_tpch_connector())
            inner.append(Worker(f"drain-w{i}", cats))
            servers.append(WorkerServer(inner[-1], require_secret=False))
            handles.append(HttpWorkerClient(servers[-1].uri))
        runner = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny"),
            worker_handles=handles, hash_partitions=2,
        )
        runner.register_catalog("tpch", create_tpch_connector())
        handles[0].set_state("SHUTTING_DOWN")
        assert handles[0].status()["state"] == "shutting_down"
        runner.node_manager.ping_once()
        states = runner.node_manager.all_states()
        assert states[handles[0].worker_id] == "drained", states
        res = runner.execute("select count(*) from nation")
        assert res.rows == [[25]]
        assert inner[0].task_ids() == []  # zero post-drain launches
    finally:
        for s in servers:
            s.stop()


# -- circuit breaker / graylist ---------------------------------------------

def _fte_runner(workers):
    session = Session(catalog="tpch", schema="tiny", retry_policy="task")
    runner = DistributedQueryRunner(session, worker_handles=workers)
    runner.register_catalog("tpch", create_tpch_connector())
    return runner


def test_graylisted_worker_gets_no_launches():
    catalogs = CatalogManager()
    catalogs.register("tpch", create_tpch_connector())
    w_ok = Worker("w-ok", catalogs)
    w_bad = DownableWorker(Worker("w-bad", catalogs))
    runner = _fte_runner([w_ok, w_bad])
    nm = runner.node_manager
    sql = "select count(*) from nation"

    # healthy cluster: both workers take launches over a few queries
    assert runner.execute(sql).rows[0][0] == 25
    assert w_bad.create_calls > 0

    # worker goes dark: failed probes trip its breaker
    w_bad.down = True
    for _ in range(3):
        nm.ping_once()
    assert nm.breaker_states()["w-bad"] == "open"

    # while graylisted: queries succeed and the dark worker receives
    # ZERO launches (placement avoids it entirely, no timeout-per-task)
    calls_while_open = w_bad.create_calls
    assert runner.execute(sql).rows[0][0] == 25
    assert w_bad.create_calls == calls_while_open

    # recovery: one successful probe closes the breaker and the worker
    # returns to rotation
    w_bad.down = False
    nm.ping_once()
    assert nm.breaker_states()["w-bad"] == "closed"
    assert runner.execute(sql).rows[0][0] == 25
    assert w_bad.create_calls > calls_while_open


def test_breaker_reopens_on_failed_probe():
    from trino_tpu.runtime.discovery import CircuitBreaker

    clock = [0.0]
    b = CircuitBreaker(trip_threshold=2, cooldown_s=1.0,
                       clock=lambda: clock[0])
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open"
    b.mark_probing()            # cooldown not elapsed
    assert b.state == "open"
    clock[0] = 2.0
    b.mark_probing()
    assert b.state == "half_open"
    b.record_failure()          # probe failed: back to open
    assert b.state == "open"
    clock[0] = 4.0
    b.mark_probing()
    b.record_success()          # probe succeeded
    assert b.state == "closed"


# -- error tracker ----------------------------------------------------------

def test_error_tracker_deterministic_backoff():
    from trino_tpu.runtime.error_tracker import (
        RequestErrorTracker,
        RetryPolicy,
    )

    def schedule(seed):
        sleeps = []
        t = RequestErrorTracker(
            "w", RetryPolicy(max_error_duration_s=1e9, max_errors=6),
            seed=seed, clock=lambda: 0.0, sleep=sleeps.append,
        )
        for _ in range(5):
            t.on_failure(ConnectionError("x"))
        return sleeps

    assert schedule(7) == schedule(7)  # replayable from the seed
    s = schedule(7)
    assert len(s) == 5 and all(x > 0 for x in s)
    # exponential shape survives the jitter (factor 2, jitter 0.25)
    assert s[3] > s[0]


def test_error_tracker_budget_and_protocol_errors():
    from trino_tpu.runtime.error_tracker import (
        RequestFailedError,
        RetryPolicy,
        run_with_retry,
    )

    pol = RetryPolicy(max_error_duration_s=0.2, min_backoff_s=0.001,
                      max_backoff_s=0.005)

    def dead():
        raise ConnectionError("down")

    with pytest.raises(RequestFailedError) as ei:
        run_with_retry("w-dead", dead, pol)
    assert len(ei.value.failures) > 1  # it DID retry before giving up

    def appfail():
        raise ValueError("application error")

    with pytest.raises(ValueError):  # non-transient: no retry loop
        run_with_retry("w-app", appfail, pol)


# -- low-memory killer ------------------------------------------------------

# A join whose build side RETAINS a non-revocable reservation during
# the probe (HashBuildSink.finish keeps the lookup source live): two
# build tasks land on each worker pool at ~434KB apiece, so a 600KB
# pool fits the first but exhausts on the second with nothing left to
# revoke — the exact shape where spill cannot save you and the killer
# must.
BIG_SQL = (
    "select o_orderpriority, count(*) c, sum(l_quantity) q "
    "from orders, lineitem where o_orderkey = l_orderkey "
    "group by o_orderpriority"
)
SMALL_SQL = "select count(*) from region"


def test_oom_kills_largest_query_only(oracle):
    """Pool exhaustion on shared worker pools — after revocation/spill
    found nothing to free — kills ONE query (the largest reservation
    holder) with a query-level ExceededMemoryLimitError; a small
    concurrent query completes, and the workers survive to serve later
    queries."""
    session = Session(
        catalog="tpch", schema="tiny", memory_pool_bytes=600 * 1024,
        mesh_execution=False,  # mesh bypasses worker pools entirely
    )
    runner = DistributedQueryRunner(session, n_workers=2)
    runner.register_catalog("tpch", create_tpch_connector())
    assert runner.memory_manager is not None

    big_err = []

    def run_big():
        try:
            runner.execute(BIG_SQL)
        except BaseException as e:
            big_err.append(e)

    t = threading.Thread(target=run_big, daemon=True)
    t.start()
    # the small query keeps working regardless of when the kill lands
    small = runner.execute(SMALL_SQL)
    assert small.rows[0][0] == 5
    t.join(120)
    assert not t.is_alive()
    assert big_err, "big query should have been killed"
    assert isinstance(big_err[0], ExceededMemoryLimitError), big_err[0]
    assert "low-memory killer" in str(big_err[0])
    assert len(runner.memory_manager.kills) == 1
    # the kill freed the victim's ledger: pools drain back to zero
    # once its tasks unwind, and the cluster still serves queries
    after = runner.execute(SMALL_SQL)
    assert after.rows[0][0] == 5
    assert runner.memory_manager.kills and not runner.memory_manager.kills[1:]
    # drain the doomed query's task threads before the interpreter
    # starts tearing down (daemon threads mid-kernel abort the process)
    for w in runner.workers:
        for k in w.task_ids():
            w.get_task(k).join(30)


# -- mid-crash after spill: spool de-duplication ----------------------------

def test_mid_crash_after_spill_no_duplicate_rows(oracle):
    """A task that spilled under memory pressure, produced output, and
    THEN died must retry without duplicating rows: consumers read only
    the committed attempt (spool manifest de-duplication), and the
    retry's spill state starts clean."""
    injector = FailureInjector()
    catalogs = CatalogManager()
    catalogs.register("tpch", create_tpch_connector())
    workers = [
        Worker(f"spill-w{i}", catalogs, failure_injector=injector,
               memory_pool_bytes=1 << 22)
        for i in range(2)
    ]
    session = Session(catalog="tpch", schema="tiny", retry_policy="task")
    runner = DistributedQueryRunner(session, worker_handles=workers)
    runner.register_catalog("tpch", create_tpch_connector())

    injector.inject(where="mid", attempts=(0,), max_hits=2)
    try:
        rows = runner.execute(Q_AGG).rows
    finally:
        injector.clear()
    expected = sqlite_rows(oracle, to_sqlite(Q_AGG))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    assert runner.last_fte_stats["retries"] >= 1
