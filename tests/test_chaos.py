"""Seeded chaos tests for the cluster resiliency layer (PR2 tentpole).

Fast tier-1 matrix: two representative TPC-H-shaped queries under every
fault class (task crash at start/mid, exchange fetch loss, straggler,
injected OOM) with a FIXED seed, asserting oracle-equal results and
bounded attempt counts. The full 22-query soak carries
@pytest.mark.slow. Graylist and low-memory-killer semantics get their
own deterministic tests (no background heartbeat thread — the probe
loop is driven by explicit ping_once calls)."""

import threading

import pytest

from tests.oracle import assert_rows_match, sqlite_rows
from tests.test_tpch import to_sqlite
from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import Session
from trino_tpu.runtime import DistributedQueryRunner, Worker
from trino_tpu.runtime.chaos import (
    FAULT_CLASSES,
    ChaosHarness,
    DownableWorker,
    generate_schedule,
)
from trino_tpu.runtime.failure import FailureInjector
from trino_tpu.runtime.memory import ExceededMemoryLimitError

SF = 0.01
SEED = 42

Q_AGG = (
    "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)
Q_JOIN = (
    "select n_name, count(*) c from supplier, nation "
    "where s_nationkey = n_nationkey "
    "group by n_name order by n_name"
)


@pytest.fixture(scope="module")
def oracle():
    import sqlite3

    from tests.oracle import load_tpch_sqlite

    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def harness():
    h = ChaosHarness(n_workers=2)
    h.register_catalog("tpch", create_tpch_connector())
    return h


# -- the seeded fault matrix ------------------------------------------------

@pytest.mark.parametrize("fault_class", FAULT_CLASSES)
@pytest.mark.parametrize("sql", [Q_AGG, Q_JOIN], ids=["agg", "join"])
def test_chaos_matrix(sql, fault_class, harness, oracle):
    rows, stats = harness.run_case(sql, fault_class, seed=SEED)
    expected = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    # attempts stay bounded by the schedule: every injected failure can
    # cause at most one retry (stalls cause speculation, not retries)
    assert stats["retries"] <= stats["max_injected_failures"], stats
    if fault_class == "fetch_loss":
        # transient fetch loss is absorbed by the exchange retry loop:
        # no task was ever re-run
        assert stats["retries"] == 0, stats


def test_schedule_determinism():
    for fc in FAULT_CLASSES:
        assert generate_schedule(SEED, fc) == generate_schedule(SEED, fc)
    assert generate_schedule(1, "task_crash_start") != generate_schedule(
        2, "task_crash_start"
    ) or True  # different seeds may collide on tiny schedules; the
    # invariant under test is same-seed stability above


@pytest.mark.slow
@pytest.mark.parametrize("fault_class", FAULT_CLASSES)
@pytest.mark.parametrize("qid", list(range(1, 23)))
def test_chaos_soak_tpch(qid, fault_class, harness, oracle):
    """The full soak: all 22 TPC-H queries under every fault class."""
    from tests.tpch_queries import QUERIES

    sql = QUERIES[qid]
    rows, stats = harness.run_case(sql, fault_class, seed=SEED + qid)
    expected = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(
        rows, expected, ordered=("order by" in sql), abs_tol=1e-2
    )
    assert stats["retries"] <= stats["max_injected_failures"]


# -- circuit breaker / graylist ---------------------------------------------

def _fte_runner(workers):
    session = Session(catalog="tpch", schema="tiny", retry_policy="task")
    runner = DistributedQueryRunner(session, worker_handles=workers)
    runner.register_catalog("tpch", create_tpch_connector())
    return runner


def test_graylisted_worker_gets_no_launches():
    catalogs = CatalogManager()
    catalogs.register("tpch", create_tpch_connector())
    w_ok = Worker("w-ok", catalogs)
    w_bad = DownableWorker(Worker("w-bad", catalogs))
    runner = _fte_runner([w_ok, w_bad])
    nm = runner.node_manager
    sql = "select count(*) from nation"

    # healthy cluster: both workers take launches over a few queries
    assert runner.execute(sql).rows[0][0] == 25
    assert w_bad.create_calls > 0

    # worker goes dark: failed probes trip its breaker
    w_bad.down = True
    for _ in range(3):
        nm.ping_once()
    assert nm.breaker_states()["w-bad"] == "open"

    # while graylisted: queries succeed and the dark worker receives
    # ZERO launches (placement avoids it entirely, no timeout-per-task)
    calls_while_open = w_bad.create_calls
    assert runner.execute(sql).rows[0][0] == 25
    assert w_bad.create_calls == calls_while_open

    # recovery: one successful probe closes the breaker and the worker
    # returns to rotation
    w_bad.down = False
    nm.ping_once()
    assert nm.breaker_states()["w-bad"] == "closed"
    assert runner.execute(sql).rows[0][0] == 25
    assert w_bad.create_calls > calls_while_open


def test_breaker_reopens_on_failed_probe():
    from trino_tpu.runtime.discovery import CircuitBreaker

    clock = [0.0]
    b = CircuitBreaker(trip_threshold=2, cooldown_s=1.0,
                       clock=lambda: clock[0])
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open"
    b.mark_probing()            # cooldown not elapsed
    assert b.state == "open"
    clock[0] = 2.0
    b.mark_probing()
    assert b.state == "half_open"
    b.record_failure()          # probe failed: back to open
    assert b.state == "open"
    clock[0] = 4.0
    b.mark_probing()
    b.record_success()          # probe succeeded
    assert b.state == "closed"


# -- error tracker ----------------------------------------------------------

def test_error_tracker_deterministic_backoff():
    from trino_tpu.runtime.error_tracker import (
        RequestErrorTracker,
        RetryPolicy,
    )

    def schedule(seed):
        sleeps = []
        t = RequestErrorTracker(
            "w", RetryPolicy(max_error_duration_s=1e9, max_errors=6),
            seed=seed, clock=lambda: 0.0, sleep=sleeps.append,
        )
        for _ in range(5):
            t.on_failure(ConnectionError("x"))
        return sleeps

    assert schedule(7) == schedule(7)  # replayable from the seed
    s = schedule(7)
    assert len(s) == 5 and all(x > 0 for x in s)
    # exponential shape survives the jitter (factor 2, jitter 0.25)
    assert s[3] > s[0]


def test_error_tracker_budget_and_protocol_errors():
    from trino_tpu.runtime.error_tracker import (
        RequestFailedError,
        RetryPolicy,
        run_with_retry,
    )

    pol = RetryPolicy(max_error_duration_s=0.2, min_backoff_s=0.001,
                      max_backoff_s=0.005)

    def dead():
        raise ConnectionError("down")

    with pytest.raises(RequestFailedError) as ei:
        run_with_retry("w-dead", dead, pol)
    assert len(ei.value.failures) > 1  # it DID retry before giving up

    def appfail():
        raise ValueError("application error")

    with pytest.raises(ValueError):  # non-transient: no retry loop
        run_with_retry("w-app", appfail, pol)


# -- low-memory killer ------------------------------------------------------

# A join whose build side RETAINS a non-revocable reservation during
# the probe (HashBuildSink.finish keeps the lookup source live): two
# build tasks land on each worker pool at ~434KB apiece, so a 600KB
# pool fits the first but exhausts on the second with nothing left to
# revoke — the exact shape where spill cannot save you and the killer
# must.
BIG_SQL = (
    "select o_orderpriority, count(*) c, sum(l_quantity) q "
    "from orders, lineitem where o_orderkey = l_orderkey "
    "group by o_orderpriority"
)
SMALL_SQL = "select count(*) from region"


def test_oom_kills_largest_query_only(oracle):
    """Pool exhaustion on shared worker pools — after revocation/spill
    found nothing to free — kills ONE query (the largest reservation
    holder) with a query-level ExceededMemoryLimitError; a small
    concurrent query completes, and the workers survive to serve later
    queries."""
    session = Session(
        catalog="tpch", schema="tiny", memory_pool_bytes=600 * 1024,
        mesh_execution=False,  # mesh bypasses worker pools entirely
    )
    runner = DistributedQueryRunner(session, n_workers=2)
    runner.register_catalog("tpch", create_tpch_connector())
    assert runner.memory_manager is not None

    big_err = []

    def run_big():
        try:
            runner.execute(BIG_SQL)
        except BaseException as e:
            big_err.append(e)

    t = threading.Thread(target=run_big, daemon=True)
    t.start()
    # the small query keeps working regardless of when the kill lands
    small = runner.execute(SMALL_SQL)
    assert small.rows[0][0] == 5
    t.join(120)
    assert not t.is_alive()
    assert big_err, "big query should have been killed"
    assert isinstance(big_err[0], ExceededMemoryLimitError), big_err[0]
    assert "low-memory killer" in str(big_err[0])
    assert len(runner.memory_manager.kills) == 1
    # the kill freed the victim's ledger: pools drain back to zero
    # once its tasks unwind, and the cluster still serves queries
    after = runner.execute(SMALL_SQL)
    assert after.rows[0][0] == 5
    assert runner.memory_manager.kills and not runner.memory_manager.kills[1:]
    # drain the doomed query's task threads before the interpreter
    # starts tearing down (daemon threads mid-kernel abort the process)
    for w in runner.workers:
        for k in w.task_ids():
            w.get_task(k).join(30)


# -- mid-crash after spill: spool de-duplication ----------------------------

def test_mid_crash_after_spill_no_duplicate_rows(oracle):
    """A task that spilled under memory pressure, produced output, and
    THEN died must retry without duplicating rows: consumers read only
    the committed attempt (spool manifest de-duplication), and the
    retry's spill state starts clean."""
    injector = FailureInjector()
    catalogs = CatalogManager()
    catalogs.register("tpch", create_tpch_connector())
    workers = [
        Worker(f"spill-w{i}", catalogs, failure_injector=injector,
               memory_pool_bytes=1 << 22)
        for i in range(2)
    ]
    session = Session(catalog="tpch", schema="tiny", retry_policy="task")
    runner = DistributedQueryRunner(session, worker_handles=workers)
    runner.register_catalog("tpch", create_tpch_connector())

    injector.inject(where="mid", attempts=(0,), max_hits=2)
    try:
        rows = runner.execute(Q_AGG).rows
    finally:
        injector.clear()
    expected = sqlite_rows(oracle, to_sqlite(Q_AGG))
    assert_rows_match(rows, expected, ordered=True, abs_tol=1e-2)
    assert runner.last_fte_stats["retries"] >= 1
