"""Long decimals (Int128) on the mesh data plane — VERDICT r4 item #3.

r4 gated decimal(>18) aggregates and group keys off the ICI plane
(mesh_plan raised MeshUnsupported), so the engine's exact-money feature
forfeited its collective exchange. These tests assert the gate is gone:
decimal(38,2) GROUP BY / sum / min / max / avg / count and long-decimal
group keys and join keys all execute through the one-SPMD-program mesh
plane (counter-asserted all_to_all > 0, fallbacks == 0), and the SAME
queries produce identical aggregates through the HTTP page plane
(mesh_execution=False) — the two data planes share the partial wire
format (reference: spi/block/Int128ArrayBlock.java rides every exchange
uniformly, optimizations/AddExchanges.java:140)."""

import collections

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import Session
from trino_tpu.parallel import mesh_plan
from trino_tpu.runtime import DistributedQueryRunner

DEC38 = T.DataType(T.TypeKind.DECIMAL, 38, 2)
N = 3000


def _i128(h, lo):
    return (int(h) << 64) + (int(lo) & ((1 << 64) - 1))


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    k = rng.integers(0, 23, N).astype(np.int64)
    # values whose per-group sums overflow int64 (hi limb exercised)
    amt = np.stack(
        [rng.integers(-4, 4, N).astype(np.int64),
         rng.integers(0, 1 << 62, N).astype(np.int64)],
        axis=-1,
    )
    dom = np.stack(
        [rng.integers(-2, 3, 7).astype(np.int64),
         rng.integers(0, 1 << 60, 7).astype(np.int64)],
        axis=-1,
    )
    dkey = dom[rng.integers(0, 7, N)]
    return k, amt, dkey


def _runner(data, mesh: bool):
    k, amt, dkey = data
    mem = create_memory_connector()
    mem.load_table(
        "t", "sales",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("amt", DEC38),
         ColumnMetadata("dkey", DEC38)],
        [k, amt, dkey], None, [None, None, None],
    )
    r = DistributedQueryRunner(
        Session(catalog="memory", schema="t", mesh_execution=mesh),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("memory", mem)
    return r


@pytest.fixture(scope="module")
def mesh_runner(data):
    return _runner(data, mesh=True)


@pytest.fixture(scope="module")
def http_runner(data):
    return _runner(data, mesh=False)


def _expected_by_key(keys, vals):
    agg = collections.defaultdict(list)
    for kk, v in zip(keys, vals):
        agg[kk].append(v)
    return agg


def _close(got_scaled_float, expected_unscaled):
    # to_pylists renders decimal(38,2) through float (exactness lives in
    # the engine; the client float is ~15 significant digits)
    return abs(got_scaled_float * 100 - expected_unscaled) <= (
        abs(expected_unscaled) * 1e-12 + 1
    )


AGG_SQL = (
    "select k, sum(amt), min(amt), max(amt), count(amt), avg(amt) "
    "from sales group by k order by k"
)


def _check_agg_rows(rows, data):
    k, amt, _ = data
    vals = [_i128(h, lo) for h, lo in amt]
    agg = _expected_by_key(k.tolist(), vals)
    assert len(rows) == len(agg)
    for row in rows:
        grp = agg[row[0]]
        assert _close(row[1], sum(grp)), ("sum", row[0])
        assert _close(row[2], min(grp)), ("min", row[0])
        assert _close(row[3], max(grp)), ("max", row[0])
        assert row[4] == len(grp), ("count", row[0])


def test_mesh_int128_aggregates(mesh_runner, data):
    before = dict(mesh_plan.MESH_COUNTERS)
    res = mesh_runner.execute(AGG_SQL)
    after = mesh_plan.MESH_COUNTERS
    assert res.data_plane == "mesh"
    assert after["all_to_all"] > before["all_to_all"]
    assert after["fallbacks"] == before["fallbacks"]
    _check_agg_rows(res.rows, data)


def test_http_int128_aggregates(http_runner, data):
    """The page plane runs the SAME partial/final split (the r4 gather
    gate in the fragmenter is gone)."""
    res = http_runner.execute(AGG_SQL)
    assert res.data_plane == "http"
    _check_agg_rows(res.rows, data)


def test_mesh_int128_group_key(mesh_runner, data):
    k, amt, dkey = data
    before = dict(mesh_plan.MESH_COUNTERS)
    res = mesh_runner.execute(
        "select dkey, count(*), sum(amt) from sales group by dkey"
    )
    after = mesh_plan.MESH_COUNTERS
    assert res.data_plane == "mesh"
    assert after["all_to_all"] > before["all_to_all"]
    assert after["fallbacks"] == before["fallbacks"]
    vals = [_i128(h, lo) for h, lo in amt]
    dk = [_i128(h, lo) for h, lo in dkey]
    agg = collections.defaultdict(lambda: [0, 0])
    for kk, v in zip(dk, vals):
        agg[kk][0] += 1
        agg[kk][1] += v
    assert len(res.rows) == len(agg)
    for row in res.rows:
        matches = [
            K for K in agg if abs(K - row[0] * 100) <= abs(K) * 1e-9 + 1
        ]
        assert matches, row[0]
        cnt, s = agg[matches[0]]
        assert row[1] == cnt
        assert _close(row[2], s)


def test_mesh_int128_join_key(mesh_runner, data):
    before = dict(mesh_plan.MESH_COUNTERS)
    res = mesh_runner.execute(
        "select count(*) from sales a, sales b "
        "where a.dkey = b.dkey and a.k = 1 and b.k = 2"
    )
    after = mesh_plan.MESH_COUNTERS
    assert res.data_plane == "mesh"
    assert after["fallbacks"] == before["fallbacks"]
    k, amt, dkey = data
    dk = [_i128(h, lo) for h, lo in dkey]
    left = [d for kk, d in zip(k, dk) if kk == 1]
    right = collections.Counter(d for kk, d in zip(k, dk) if kk == 2)
    expected = sum(right[d] for d in left)
    assert res.rows[0][0] == expected


def test_global_int128_aggregates(mesh_runner, data):
    """GROUP-BY-less partial -> gather -> final over the Int128 wire
    state (one (1, 2) limb-pair row per shard)."""
    k, amt, _ = data
    res = mesh_runner.execute(
        "select sum(amt), min(amt), max(amt), count(amt) from sales"
    )
    vals = [_i128(h, lo) for h, lo in amt]
    row = res.rows[0]
    assert _close(row[0], sum(vals))
    assert _close(row[1], min(vals))
    assert _close(row[2], max(vals))
    assert row[3] == N
