"""decimal(19..38) — Int128-carried decimals (VERDICT r3 item #2).

Covers Trino's DecimalOperators result typing (reference
main/type/DecimalOperators.java longVariables), Int128 arithmetic
correctness vs Python's exact Decimal, aggregation (sum -> decimal(38,s),
limb-split accumulators), comparisons, ORDER BY, GROUP BY / join keys on
long decimals, and the wire round trip.
"""

from decimal import Decimal, ROUND_HALF_UP, getcontext

import pytest

from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.engine import LocalQueryRunner, Session

getcontext().prec = 80


@pytest.fixture(scope="module")
def r():
    r = LocalQueryRunner(Session(catalog="memory", schema="t"))
    r.register_catalog("memory", create_memory_connector())
    r.execute(
        "create table memory.t.big (a decimal(30,4), b decimal(30,4), k bigint)"
    )
    r.execute(
        "insert into big values "
        "(12345678901234567890123456.7890, 2.0000, 1), "
        "(-9999999999999999999999.9999, 3.5000, 1), "
        "(0.0001, -1.0000, 2), "
        "(7777777777777777777777.7777, 0.5000, 2), "
        "(null, 1.0000, 3)"
    )
    return r


VALS = [
    Decimal("12345678901234567890123456.7890"),
    Decimal("-9999999999999999999999.9999"),
    Decimal("0.0001"),
    Decimal("7777777777777777777777.7777"),
    None,
]
BVALS = [Decimal("2"), Decimal("3.5"), Decimal("-1"), Decimal("0.5"), Decimal("1")]


def q2dec(x, scale):
    return (
        None
        if x is None
        else Decimal(str(x)).quantize(Decimal(1).scaleb(-scale))
    )


class TestTyping:
    def test_literal_and_cast(self, r):
        res = r.execute("select cast('1' as decimal(38,10))")
        assert str(res.column_types[0]) == "decimal(38,10)"
        assert res.rows == [[1.0]]

    def test_add_result_type(self, r):
        res = r.execute("select a + b from big where k = 2")
        # (30,4)+(30,4): p = min(38, 26+4+1) = 31
        assert str(res.column_types[0]) == "decimal(31,4)"

    def test_mul_result_type(self, r):
        res = r.execute("select b * b from big where k = 2")
        assert str(res.column_types[0]) == "decimal(38,8)"

    def test_div_result_type(self, r):
        res = r.execute("select a / b from big where k = 2")
        # p1 + s2 + max(s2-s1, 0) = 30 + 4 + 0 = 34
        assert str(res.column_types[0]) == "decimal(34,4)"

    def test_sum_is_38(self, r):
        res = r.execute("select sum(a) from big")
        assert str(res.column_types[0]) == "decimal(38,4)"


class TestArithmetic:
    def test_add_exact(self, r):
        got = sorted(
            Decimal(str(v))
            for (v,) in r.execute(
                "select a + b from big where a is not null"
            ).rows
        )
        want = sorted(v + b for v, b in zip(VALS, BVALS) if v is not None)
        for g, w in zip(got, want):
            tol = max(Decimal(1), abs(w)) * Decimal("1e-12")
            assert abs(g - w) <= tol, (g, w)

    def test_mul_exact_midsize(self, r):
        got = r.execute("select b * b from big order by k, b").rows
        assert len(got) == 5

    def test_div_half_up(self, r):
        (v,) = r.execute(
            "select cast(7 as decimal(20,0)) / cast(2 as decimal(20,0))"
        ).rows[0]
        # scale 0, HALF_UP: 7/2 -> 4 (Trino rounds half up)
        assert v == 4

    def test_sum_exact(self, r):
        (got,) = r.execute("select sum(a) from big").rows[0]
        want = sum(v for v in VALS if v is not None)
        assert abs(Decimal(str(got)) - want) < abs(want) * Decimal("1e-12")

    def test_group_sum_and_keys(self, r):
        rows = r.execute(
            "select k, sum(a), count(a) from big group by k order by k"
        ).rows
        assert [row[0] for row in rows] == [1, 2, 3]
        assert rows[2][1] is None and rows[2][2] == 0

    def test_min_max_global(self, r):
        (mn, mx) = r.execute("select min(a), max(a) from big").rows[0]
        reals = [v for v in VALS if v is not None]
        assert abs(Decimal(str(mn)) - min(reals)) < abs(min(reals)) * Decimal("1e-12")
        assert abs(Decimal(str(mx)) - max(reals)) < abs(max(reals)) * Decimal("1e-12")

    def test_avg_long(self, r):
        (got,) = r.execute("select avg(a) from big where k = 2").rows[0]
        want = (Decimal("0.0001") + Decimal("7777777777777777777777.7777")) / 2
        # client protocol renders decimals as float64: 17 significant
        # digits round-trip; the device value itself is exact
        assert abs(Decimal(str(got)) - want) <= abs(want) * Decimal("1e-15")


class TestRelational:
    def test_compare_and_filter(self, r):
        rows = r.execute("select k from big where a > 0 order by a").rows
        assert [k for (k,) in rows] == [2, 2, 1]

    def test_order_by_long(self, r):
        rows = r.execute(
            "select a from big where a is not null order by a desc"
        ).rows
        vals = [Decimal(str(v)) for (v,) in rows]
        assert vals == sorted(vals, reverse=True)

    def test_group_by_long_key(self, r):
        rows = r.execute(
            "select a, count(*) from big group by a order by count(*), a"
        ).rows
        assert len(rows) == 5  # 4 distinct + NULL group

    def test_join_on_long_key(self, r):
        rows = r.execute(
            "select count(*) from big x join big y on x.a = y.a"
        ).rows
        assert rows == [[4]]  # NULL keys never match

    def test_between_long(self, r):
        rows = r.execute(
            "select count(*) from big where a between -1e22 and 1e25"
        ).rows
        assert rows == [[3]]

    def test_case_unifies_short_and_long(self, r):
        rows = r.execute(
            "select sum(case when k = 1 then a else 0 end) from big"
        ).rows
        want = VALS[0] + VALS[1]
        assert abs(Decimal(str(rows[0][0])) - want) < abs(want) * Decimal("1e-12")


class TestFullDivision:
    """128/128 division — divisors beyond int64 (VERDICT r4 item #4;
    reference spi/type/Int128Math.java full divide). HALF_UP rounding,
    remainder takes the dividend's sign."""

    @pytest.fixture(scope="class")
    def rd(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="t"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("create table memory.t.dd (a decimal(38,2), b decimal(38,2))")
        r.execute(
            "insert into dd values "
            "(12345678901234567890123456789012.45, 98765432109876543210987654.32), "
            "(-9999999999999999999999999999999.99, 12345678901234567890.12), "
            "(1.00, 33333333333333333333333333333333.33), "
            "(-5000000000000000000000000000000.00, -7000000000000000000000000000000.00)"
        )
        return r

    def test_div_128_divisor(self, rd):
        res = rd.execute("select a / b from dd")
        out_t = res.column_types[0]
        scale = out_t.scale or 0
        rows = res.rows
        a_vals = [
            Decimal("12345678901234567890123456789012.45"),
            Decimal("-9999999999999999999999999999999.99"),
            Decimal("1.00"),
            Decimal("-5000000000000000000000000000000.00"),
        ]
        b_vals = [
            Decimal("98765432109876543210987654.32"),
            Decimal("12345678901234567890.12"),
            Decimal("33333333333333333333333333333333.33"),
            Decimal("-7000000000000000000000000000000.00"),
        ]
        for (got,), a, b in zip(rows, a_vals, b_vals):
            # Trino divide typing (DecimalOperators): round HALF_UP at
            # the RESULT type's scale
            exp = float(
                (a / b).quantize(
                    Decimal(1).scaleb(-scale), rounding=ROUND_HALF_UP
                )
            )
            assert got is not None
            assert abs(got - exp) <= abs(exp) * 1e-9 + 1e-6, (got, exp)

    def test_mod_128_divisor(self, rd):
        rows = rd.execute("select a % b from dd").rows
        a_vals = [
            Decimal("12345678901234567890123456789012.45"),
            Decimal("-9999999999999999999999999999999.99"),
            Decimal("1.00"),
            Decimal("-5000000000000000000000000000000.00"),
        ]
        b_vals = [
            Decimal("98765432109876543210987654.32"),
            Decimal("12345678901234567890.12"),
            Decimal("33333333333333333333333333333333.33"),
            Decimal("-7000000000000000000000000000000.00"),
        ]
        for (got,), a, b in zip(rows, a_vals, b_vals):
            m = abs(a) % abs(b)
            exp = float(m if a >= 0 else -m)
            assert got is not None
            assert abs(got - exp) <= abs(exp) * 1e-9 + 1e-6, (got, exp)

    def test_div_overflow_nulls(self, rd):
        # rescaled dividend beyond 2^127: documented NULL (Trino raises
        # NUMERIC_VALUE_OUT_OF_RANGE; deviation recorded in analyzer.py)
        rows = rd.execute(
            "select a / 0.000001 from dd where a < -1e30"
        ).rows
        assert all(v is None for (v,) in rows)


class TestHolisticLongDecimal:
    """min_by/max_by with Int128 `by` and `x` columns (grouped_argbest
    lexicographic limb reduce; was silently wrong before r5)."""

    @pytest.fixture(scope="class")
    def rh(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="t"))
        r.register_catalog("memory", create_memory_connector())
        r.execute(
            "create table memory.t.hb (k bigint, x decimal(38,2), y bigint)"
        )
        r.execute(
            "insert into hb values "
            "(1, 99999999999999999999999999999999999.01, 10), "
            "(1, -99999999999999999999999999999999999.02, 20), "
            "(1, 5.00, 30), "
            "(2, 12345678901234567890123456789.00, 40), "
            "(2, 12345678901234567890123456788.99, 50)"
        )
        return r

    def test_min_by_long_decimal_by(self, rh):
        rows = rh.execute(
            "select k, min_by(y, x), max_by(y, x) from hb group by k order by k"
        ).rows
        assert rows == [[1, 20, 10], [2, 50, 40]]

    def test_min_max_with_holistic_mix(self, rh):
        # a holistic aggregate alongside an Int128 extreme exercises the
        # _finish_holistic slots->state path (review finding r5)
        rows = rh.execute(
            "select k, min(x), min_by(y, x) from hb group by k order by k"
        ).rows
        assert rows[0][2] == 20 and rows[1][2] == 50
        assert abs(rows[0][1] - (-1e35)) < 1e23


class TestWindowValueFns:
    """lead/lag/first/last/nth over Int128 limb-pair columns gather
    row-wise (r5: take without axis flattened (n,2) arrays)."""

    def test_lead_lag_first_last_over_long_decimal(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="t"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("create table memory.t.wd (d decimal(38,2), g bigint)")
        r.execute(
            "insert into wd values (1.50, 1), "
            "(99999999999999999999999999999999.00, 2), (3.25, 3)"
        )
        rows = r.execute(
            "select lead(d) over (order by g), lag(d) over (order by g),"
            " first_value(d) over (order by g) from wd"
        ).rows
        assert rows[0][0] == 1e32 and rows[0][1] is None
        assert rows[1][1] == 1.5
        assert all(row[2] == 1.5 for row in rows)
