"""Expression engine tests — IR lowering vs a python oracle.

Tier-1 analogue of Trino's operator/scalar and TestPageProcessor tests
(SURVEY.md §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch
from trino_tpu.expr import ir
from trino_tpu.expr.compile import ExprBinder, bind_expr


def batch_of(schema, data):
    return RelBatch.from_pydict(schema, data)


def col(i, t):
    return ir.InputRef(i, t)


def lit(v, t):
    return ir.Literal(v, t)


SCHEMA = [
    ("a", T.BIGINT),
    ("b", T.BIGINT),
    ("d", T.DOUBLE),
    ("s", T.VARCHAR),
    ("p", T.decimal(12, 2)),
]
DATA = {
    "a": [1, 2, None, 4, 5],
    "b": [10, None, 30, 40, 0],
    "d": [1.5, -2.5, 3.0, None, 0.0],
    "s": ["apple", "banana", None, "cherry", "apple"],
    "p": [1.25, 2.50, 3.75, None, -1.00],
}


@pytest.fixture(scope="module")
def batch():
    return batch_of(SCHEMA, DATA)


def run(expr, batch, count=5):
    out = bind_expr(expr, batch).eval_batch(batch)
    return out.to_pylist(count=count)


def test_arith_add(batch):
    assert run(ir.call("add", T.BIGINT, col(0, T.BIGINT), col(1, T.BIGINT)), batch) == [
        11, None, None, 44, 5]


def test_arith_mul_literal(batch):
    assert run(ir.call("mul", T.BIGINT, col(0, T.BIGINT), lit(3, T.BIGINT)), batch) == [
        3, 6, None, 12, 15]


def test_int_division_by_zero_is_null(batch):
    out = run(ir.call("div", T.BIGINT, col(0, T.BIGINT), col(1, T.BIGINT)), batch)
    assert out == [0, None, None, 0, None]


def test_comparison_and_3vl(batch):
    # a > 1 AND b > 10: NULL AND x rules
    e = ir.and_(
        ir.comparison("gt", col(0, T.BIGINT), lit(1, T.BIGINT)),
        ir.comparison("gt", col(1, T.BIGINT), lit(10, T.BIGINT)),
    )
    # rows: (1,10)->F, (2,NULL)->NULL, (NULL,30)->NULL, (4,40)->T, (5,0)->F
    assert run(e, batch) == [False, None, None, True, False]


def test_or_3vl(batch):
    e = ir.or_(
        ir.comparison("gt", col(0, T.BIGINT), lit(3, T.BIGINT)),
        ir.comparison("gt", col(1, T.BIGINT), lit(10, T.BIGINT)),
    )
    # (1,10)->F|F=F, (2,NULL)->F|N=N, (NULL,30)->N|T=T, (4,40)->T, (5,0)->T|F=T
    assert run(e, batch) == [False, None, True, True, True]


def test_not_null(batch):
    e = ir.not_(ir.is_null(col(0, T.BIGINT)))
    assert run(e, batch) == [True, True, False, True, True]


def test_string_eq_literal(batch):
    e = ir.comparison("eq", col(3, T.VARCHAR), lit("apple", T.VARCHAR))
    assert run(e, batch) == [True, False, None, False, True]


def test_string_lt_absent_literal(batch):
    # 'b' sorts between 'apple' and 'banana'
    e = ir.comparison("lt", col(3, T.VARCHAR), lit("b", T.VARCHAR))
    assert run(e, batch) == [True, False, None, False, True]


def test_string_literal_on_left(batch):
    # 'b' < s  ⇔  s > 'b'
    e = ir.comparison("lt", lit("b", T.VARCHAR), col(3, T.VARCHAR))
    assert run(e, batch) == [False, True, None, True, False]


def test_string_eq_absent_literal(batch):
    e = ir.comparison("eq", col(3, T.VARCHAR), lit("mango", T.VARCHAR))
    assert run(e, batch) == [False, False, None, False, False]


def test_like(batch):
    e = ir.Call("like", (col(3, T.VARCHAR), lit("%an%", T.VARCHAR)), T.BOOLEAN)
    assert run(e, batch) == [False, True, None, False, False]


def test_substr(batch):
    e = ir.Call(
        "substr", (col(3, T.VARCHAR), lit(1, T.BIGINT), lit(3, T.BIGINT)), T.VARCHAR
    )
    assert run(e, batch) == ["app", "ban", None, "che", "app"]


def test_in_list(batch):
    e = ir.InList(col(3, T.VARCHAR), (lit("apple", T.VARCHAR), lit("mango", T.VARCHAR)))
    assert run(e, batch) == [True, False, None, False, True]


def test_case(batch):
    e = ir.Case(
        conds=(ir.comparison("gt", col(0, T.BIGINT), lit(3, T.BIGINT)),
               ir.comparison("gt", col(0, T.BIGINT), lit(1, T.BIGINT))),
        results=(lit(100, T.BIGINT), lit(200, T.BIGINT)),
        default=lit(0, T.BIGINT),
        type=T.BIGINT,
    )
    assert run(e, batch) == [0, 200, 0, 100, 100]


def test_case_null_default(batch):
    e = ir.Case(
        conds=(ir.comparison("gt", col(0, T.BIGINT), lit(3, T.BIGINT)),),
        results=(lit(1, T.BIGINT),),
        default=None,
        type=T.BIGINT,
    )
    assert run(e, batch) == [None, None, None, 1, 1]


def test_coalesce(batch):
    e = ir.Call("coalesce", (col(0, T.BIGINT), col(1, T.BIGINT)), T.BIGINT)
    assert run(e, batch) == [1, 2, 30, 4, 5]


def test_decimal_add(batch):
    t = T.decimal(12, 2)
    e = ir.call("add", t, col(4, t), col(4, t))
    assert run(e, batch) == [2.5, 5.0, 7.5, None, -2.0]


def test_decimal_mul_scale(batch):
    # p * p -> scale 4
    t = T.decimal(18, 4)
    e = ir.call("mul", t, col(4, T.decimal(12, 2)), col(4, T.decimal(12, 2)))
    assert run(e, batch) == [1.5625, 6.25, 14.0625, None, 1.0]


def test_decimal_one_minus(batch):
    # TPC-H staple: (1 - p)
    t = T.decimal(18, 2)
    e = ir.call("sub", t, lit(1, T.BIGINT), col(4, T.decimal(12, 2)))
    assert run(e, batch) == [-0.25, -1.5, -2.75, None, 2.0]


def test_decimal_div(batch):
    t = T.decimal(18, 2)
    e = ir.call("div", t, col(4, T.decimal(12, 2)), lit(2, T.BIGINT))
    # 1.25/2=0.63 (half away), 2.50/2=1.25, 3.75/2=1.88, NULL, -0.50
    assert run(e, batch) == [0.63, 1.25, 1.88, None, -0.5]


def test_decimal_compare(batch):
    e = ir.comparison("ge", col(4, T.decimal(12, 2)), lit(2.5, T.decimal(12, 2)))
    assert run(e, batch) == [False, True, True, None, False]


def test_cast_decimal_to_double(batch):
    e = ir.Cast(col(4, T.decimal(12, 2)), T.DOUBLE)
    assert run(e, batch) == [1.25, 2.5, 3.75, None, -1.0]


def test_extract_year():
    b = batch_of([("dt", T.DATE)], {"dt": [0, 10957, 19723]})  # 1970-01-01, 2000-01-01, 2024-01-01
    e = ir.Call("extract_year", (col(0, T.DATE),), T.BIGINT)
    assert run(e, b, count=3) == [1970, 2000, 2024]


def test_extract_month_day():
    import datetime
    days = [(datetime.date(1995, 3, 17) - datetime.date(1970, 1, 1)).days]
    b = batch_of([("dt", T.DATE)], {"dt": days})
    assert run(ir.Call("extract_month", (col(0, T.DATE),), T.BIGINT), b, 1) == [3]
    assert run(ir.Call("extract_day", (col(0, T.DATE),), T.BIGINT), b, 1) == [17]


def test_mod_sign(batch):
    e = ir.call("mod", T.BIGINT, lit(-7, T.BIGINT), lit(3, T.BIGINT))
    assert run(e, batch)[0] == -1  # SQL mod keeps dividend sign


def test_bound_under_jit(batch):
    """The bound closure must trace cleanly under jax.jit."""
    e = ir.call("add", T.BIGINT, col(0, T.BIGINT), col(1, T.BIGINT))
    bound = bind_expr(e, batch)

    @jax.jit
    def go(cols, valids):
        return bound.fn(cols, valids)

    d, v = go([c.data for c in batch.columns], [c.valid for c in batch.columns])
    assert int(d[0]) == 11


# ---- regressions from review findings ----


def test_coalesce_priority():
    b = batch_of([("a", T.BIGINT)], {"a": [1, 2, None, 4, 5]})
    e = ir.Call(
        "coalesce",
        (col(0, T.BIGINT), lit(7, T.BIGINT), col(0, T.BIGINT)),
        T.BIGINT,
    )
    assert run(e, b) == [1, 2, 7, 4, 5]


def test_decimal_vs_integer_compare():
    b = batch_of([("p", T.decimal(12, 2))], {"p": [1.25, 2.5, 3.75, None, -1.0]})
    e = ir.comparison("ge", col(0, T.decimal(12, 2)), lit(2, T.BIGINT))
    assert run(e, b) == [False, True, True, None, False]


def test_integer_division_truncates():
    b = batch_of([("a", T.BIGINT)], {"a": [-7, 7, -7, 7]})
    e = ir.call("div", T.BIGINT, col(0, T.BIGINT), lit(2, T.BIGINT))
    assert run(e, b, count=4) == [-3, 3, -3, 3]


def test_single_value_string_column_keeps_nulls():
    b = batch_of([("s", T.VARCHAR), ("t", T.VARCHAR)],
                 {"s": ["x", None, "x"], "t": ["x", "x", "y"]})
    e = ir.comparison("eq", col(0, T.VARCHAR), col(1, T.VARCHAR))
    assert run(e, b, count=3) == [True, None, False]


def test_round_with_scale():
    b = batch_of([("d", T.DOUBLE)], {"d": [1.234, -2.345, 2.5]})
    e = ir.Call("round", (col(0, T.DOUBLE), lit(2, T.BIGINT)), T.DOUBLE)
    assert run(e, b, count=3) == [1.23, -2.35, 2.5]
    e0 = ir.Call("round", (col(0, T.DOUBLE),), T.DOUBLE)
    assert run(e0, b, count=3) == [1.0, -2.0, 3.0]  # half away from zero


def test_cast_half_away():
    b = batch_of([("d", T.DOUBLE)], {"d": [-2.5, 2.5, 0.125]})
    e = ir.Cast(col(0, T.DOUBLE), T.BIGINT)
    assert run(e, b, count=3) == [-3, 3, 0]


def test_in_list_with_null_option():
    b = batch_of([("a", T.BIGINT)], {"a": [1, 2, 3]})
    e = ir.InList(col(0, T.BIGINT), (lit(1, T.BIGINT), lit(None, T.BIGINT)))
    assert run(e, b, count=3) == [True, None, None]


def test_empty_or_is_false():
    assert isinstance(ir.or_(), ir.Literal)
    assert ir.or_().value is False


def test_floor_on_decimal():
    b = batch_of([("p", T.decimal(12, 2))], {"p": [1.25, -1.25, 3.0]})
    e = ir.Call("floor", (col(0, T.decimal(12, 2)),), T.BIGINT)
    assert run(e, b, count=3) == [1, -2, 3]


def test_substr_negative_start():
    b = batch_of([("s", T.VARCHAR)], {"s": ["hello"]})
    e = ir.Call("substr", (col(0, T.VARCHAR), lit(-2, T.BIGINT)), T.VARCHAR)
    assert run(e, b, count=1) == ["lo"]
    e0 = ir.Call("substr", (col(0, T.VARCHAR), lit(0, T.BIGINT)), T.VARCHAR)
    assert run(e0, b, count=1) == [""]


def test_string_fn_on_null_literal():
    b = batch_of([("a", T.BIGINT)], {"a": [1, 2]})
    e = ir.Call("length", (ir.Cast(lit(None, T.UNKNOWN), T.VARCHAR),), T.BIGINT)
    assert run(e, b, count=2) == [None, None]


def test_all_null_string_column_like():
    b = batch_of([("s", T.VARCHAR)], {"s": [None, None]})
    e = ir.Call("like", (col(0, T.VARCHAR), lit("a%", T.VARCHAR)), T.BOOLEAN)
    assert run(e, b, count=2) == [None, None]


def test_extract_year_negative_days():
    b = batch_of([("dt", T.DATE)], {"dt": [-1, -365]})  # 1969-12-31, 1969-01-01
    e = ir.Call("extract_year", (col(0, T.DATE),), T.BIGINT)
    assert run(e, b, count=2) == [1969, 1969]


def test_cast_preserves_constness():
    b = batch_of([("d", T.DOUBLE)], {"d": [1.234]})
    e = ir.Call(
        "round",
        (col(0, T.DOUBLE), ir.Cast(lit(1, T.INTEGER), T.BIGINT)),
        T.DOUBLE,
    )
    assert run(e, b, count=1) == [1.2]


def test_float_div_by_zero_is_infinite():
    b = batch_of([("d", T.DOUBLE)], {"d": [1.0, -1.0, 0.0]})
    e = ir.call("div", T.DOUBLE, col(0, T.DOUBLE), lit(0.0, T.DOUBLE))
    out = run(e, b, count=3)
    assert out[0] == float("inf") and out[1] == float("-inf")
    assert out[2] != out[2]  # NaN


def test_decimal_literal_half_away():
    b = batch_of([("p", T.decimal(3, 2))], {"p": [0.13]})
    e = ir.comparison("eq", col(0, T.decimal(3, 2)), lit(0.125, T.decimal(3, 2)))
    assert run(e, b, count=1) == [True]  # 0.125 -> 0.13 half away, not 0.12
