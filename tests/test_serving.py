"""Serving tier (PR 8): prepared-statement plan cache, typed EXECUTE
parameter binding, lane-based admission with overload shedding,
inter-query micro-batching, and the worker-local deadline check.

The plan-cache tests assert BOTH halves of the contract: a hit must be
observable in the counters (or the cache is decorative) AND the reused
plan must produce oracle-equal rows (or the cache is wrong). Property
flips and DML must miss/invalidate — a stale physical plan captures
split listings, i.e. a data snapshot.
"""

import threading
import time
import urllib.error

import pytest

from tests.oracle import assert_rows_match, oracle_rows
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.serving.admission import (
    AdmissionPipeline,
    OverloadSheddedError,
    fast_path_probe,
)
from trino_tpu.serving.batcher import MicroBatcher, classify
from trino_tpu.serving.params import ParameterBindingError
from trino_tpu.serving.plan_cache import PlanCache

SF = 0.01

Q_POINT = "select o_custkey, o_totalprice from orders where o_orderkey = 7"
Q_AGG = (
    "select l_returnflag, count(*) c from lineitem "
    "group by l_returnflag order by l_returnflag"
)


@pytest.fixture()
def runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


# -- plan cache -------------------------------------------------------------


def test_plan_cache_hit_is_oracle_equal(runner):
    cache = runner._plan_cache
    first = runner.execute(Q_AGG).rows
    h0 = cache.hits
    # a whitespace/case variant must canonicalize onto the same entry
    variant = Q_AGG.replace("select", "SELECT  ").replace("  c ", " c ")
    again = runner.execute(variant).rows
    assert cache.hits > h0, cache.stats()
    expected = oracle_rows(SF, Q_AGG)
    assert_rows_match(first, expected, ordered=True)
    assert_rows_match(again, expected, ordered=True)


def test_plan_cache_property_change_misses(runner):
    runner.execute(Q_POINT)
    cache = runner._plan_cache
    m0, h0 = cache.misses, cache.hits
    runner.execute(Q_POINT)
    assert cache.hits == h0 + 1 and cache.misses == m0
    # flipping a plan-affecting session property must MISS, not serve
    # the stale shape (SET SESSION never needs to invalidate)
    runner.session.enable_dynamic_filtering = (
        not runner.session.enable_dynamic_filtering
    )
    rows = runner.execute(Q_POINT).rows
    assert cache.misses == m0 + 1, cache.stats()
    assert_rows_match(rows, oracle_rows(SF, Q_POINT), ordered=False)


def test_plan_cache_invalidated_by_dml():
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", create_memory_connector())
    r.execute("CREATE TABLE t (a bigint)")
    r.execute("INSERT INTO t VALUES (1), (2)")
    assert r.execute("SELECT count(*) FROM t").only_value() == 2
    inv0 = r._plan_cache.invalidations
    r.execute("INSERT INTO t VALUES (3)")
    assert r._plan_cache.invalidations > inv0
    # the recount must NOT come from a plan that captured the old splits
    assert r.execute("SELECT count(*) FROM t").only_value() == 3


def test_plan_cache_lru_bound():
    c = PlanCache(max_entries=2)
    s = Session(catalog="tpch", schema="tiny")
    keys = [c.key(f"select {i}", s) for i in range(3)]
    for i, k in enumerate(keys):
        c.store(k, ("plan", i))
    assert len(c) == 2 and c.evictions == 1
    assert c.lookup(keys[0]) is None  # oldest evicted
    assert c.lookup(keys[2]) == ("plan", 2)
    # lookup refreshes recency: storing a 4th now evicts keys[1]
    c.store(c.key("select 3", s), ("plan", 3))
    assert c.lookup(keys[2]) == ("plan", 2)
    assert c.lookup(keys[1]) is None


def test_plan_cache_stale_generation_not_stored():
    c = PlanCache()
    s = Session(catalog="tpch", schema="tiny")
    k = c.key("select 1", s)
    gen = c.generation
    c.invalidate()  # DDL lands while the planner is mid-flight
    c.store(k, "stale-plan", generation=gen)
    assert c.contains(k) is False


# -- typed EXECUTE ... USING binding ----------------------------------------


def test_execute_using_repeat_binding_hits_cache(runner):
    runner.execute(
        "PREPARE pq FROM select o_custkey from orders where o_orderkey = ?"
    )
    cache = runner._plan_cache
    first = runner.execute("EXECUTE pq USING 7").rows
    h0 = cache.hits
    again = runner.execute("EXECUTE pq USING 7").rows
    assert cache.hits > h0, cache.stats()
    assert first == again
    assert_rows_match(
        first,
        oracle_rows(SF, "select o_custkey from orders where o_orderkey = 7"),
        ordered=False,
    )


def test_execute_using_arity_error(runner):
    runner.execute(
        "PREPARE p1 FROM select o_custkey from orders where o_orderkey = ?"
    )
    with pytest.raises(ParameterBindingError, match="expects 1 parameter"):
        runner.execute("EXECUTE p1 USING 1, 2")


def test_execute_using_dtype_error(runner):
    runner.execute(
        "PREPARE p2 FROM select o_custkey from orders where o_orderkey = ?"
    )
    with pytest.raises(
        ParameterBindingError, match="expected bigint, got varchar"
    ):
        runner.execute("EXECUTE p2 USING 'not-a-key'")


# -- admission + shedding ---------------------------------------------------


def test_admission_sheds_past_depth():
    p = AdmissionPipeline(None, fast_depth=1, general_depth=2,
                          retry_after_s=0.75)
    held = [p.reserve(fast=False), p.reserve(fast=False)]
    with pytest.raises(OverloadSheddedError) as ei:
        p.reserve(fast=False)
    assert ei.value.retry_after_s == 0.75
    # the fast lane is independent capacity: still admits
    f = p.reserve(fast=True)
    with pytest.raises(OverloadSheddedError):
        p.reserve(fast=True)
    for r in held + [f]:
        p.release(r)
        p.release(r)  # idempotent
    assert p.reserve(fast=False).lane == "general"


def test_server_sheds_with_429_and_retry_after(runner):
    from trino_tpu.client import Client
    from trino_tpu.runtime.server import CoordinatorServer

    server = CoordinatorServer(
        runner,
        max_concurrent=6,
        admission=AdmissionPipeline(None, fast_depth=1, general_depth=2,
                                    retry_after_s=0.5),
    )
    codes = []
    lock = threading.Lock()

    def go():
        c = Client(server.uri, timeout=30.0, poll_interval=0.005)
        try:
            c.execute(Q_AGG)
            with lock:
                codes.append("ok")
        except urllib.error.HTTPError as e:
            with lock:
                codes.append((e.code, e.headers.get("Retry-After")))

    try:
        ts = [threading.Thread(target=go) for _ in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
    finally:
        server.stop()
    shed = [c for c in codes if c != "ok"]
    assert codes.count("ok") >= 2, codes  # admitted work still finishes
    assert shed and all(c == (429, "0.5") for c in shed), codes


def test_fast_path_probe_requires_warm_plan(runner):
    assert fast_path_probe(runner, Q_POINT, None) is False  # cold
    runner.execute(Q_POINT)
    assert fast_path_probe(runner, Q_POINT, None) is True  # warm
    assert fast_path_probe(runner, Q_AGG, None) is False  # not a point


# -- micro-batching ---------------------------------------------------------


def test_classify_is_strict(runner):
    ok = classify(Q_POINT)
    assert ok is not None and ok.value == 7 and ok.key_col == "o_orderkey"
    for sql in (
        Q_AGG,  # aggregate
        "select o_custkey from orders where o_orderkey = 1.5",  # float key
        "select o_custkey from orders where o_orderkey > 7",  # range
        "select o_custkey from orders where o_orderkey = 1 limit 1",
        "select o_custkey c from orders where o_orderkey = 1",  # alias
        "select o_custkey from orders o where o_orderkey = 1",  # table alias
    ):
        assert classify(sql) is None, sql
    # EXECUTE resolves through the request-prepared dict
    look = classify(
        "EXECUTE pp USING 9",
        prepared={
            "pp": "select o_custkey from orders where o_orderkey = ?"
        },
    )
    assert look is not None and look.value == 9


def test_batcher_demux_interleaved_clients(runner):
    keys = [1, 2, 3, 7, 7, 32, 33, 2]  # duplicates on purpose
    expected = {
        k: runner.execute(
            f"select o_custkey, o_totalprice from orders "
            f"where o_orderkey = {k}"
        ).rows
        for k in set(keys)
    }
    b = MicroBatcher(runner, window_s=0.25, max_batch=len(keys))
    results: dict = {}
    errors: list = []

    def go(i, k):
        try:
            res = b.submit(
                f"select o_custkey, o_totalprice from orders "
                f"where o_orderkey = {k}"
            )
            results[i] = (k, res.rows)
        except Exception as e:  # pragma: no cover - diagnostic
            errors.append(e)

    ts = [
        threading.Thread(target=go, args=(i, k))
        for i, k in enumerate(keys)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errors
    assert len(results) == len(keys)
    # every client got exactly ITS key's rows, not a neighbor's
    for i, k in enumerate(keys):
        got_k, rows = results[i]
        assert got_k == k and rows == expected[k], (i, k, rows)
    st = b.stats()
    assert st["batched_queries"] == len(keys)
    assert 1 <= st["batches"] < len(keys), st  # coalescing happened
    assert st["open_groups"] == 0


def test_batcher_propagates_shared_failure(runner):
    b = MicroBatcher(runner, window_s=0.01, max_batch=4)
    with pytest.raises(Exception):
        b.submit("select no_such_col from orders where o_orderkey = 1")
    assert b.stats()["open_groups"] == 0


# -- worker-local deadline --------------------------------------------------


def test_on_batch_enforces_local_deadline():
    from trino_tpu import types as T
    from trino_tpu.runtime.task import TaskExecution, TaskId, TaskSpec
    from trino_tpu.sql.fragmenter import PlanFragment
    from trino_tpu.sql.plan import Field, ValuesNode

    node = ValuesNode((Field("a", T.BIGINT),), ((1,), (2,)))
    frag = PlanFragment(0, node, "single", "single")

    def spec(deadline):
        return TaskSpec(
            task_id=TaskId("q0", 0, 0),
            fragment=frag,
            n_output_partitions=1,
            remote_schemas={},
            scan_slice=None,
            input_locations={},
            deadline_epoch_s=deadline,
        )

    # expired deadline: the batch-boundary check fails the task itself,
    # with the typed code in the travelled message
    t = TaskExecution(spec(time.time() - 5.0), None)
    t._on_batch("scan", True)
    assert t.state == "failed"
    assert "EXCEEDED_TIME_LIMIT" in (t.failure or "")
    assert "worker-local deadline" in t.failure
    # live deadline: no effect
    t2 = TaskExecution(spec(time.time() + 60.0), None)
    t2._on_batch("scan", True)
    assert t2.state != "failed"
    # no deadline: no effect
    t3 = TaskExecution(spec(None), None)
    t3._on_batch("scan", True)
    assert t3.state != "failed"


# -- harness plumbing -------------------------------------------------------


def test_exact_percentile():
    from trino_tpu.serving.harness import exact_percentile

    assert exact_percentile([], 0.5) == 0.0
    assert exact_percentile([3.0], 0.99) == 3.0
    xs = [float(i) for i in range(1, 101)]
    assert exact_percentile(xs, 0.0) == 1.0
    assert exact_percentile(xs, 0.5) == 51.0
    assert exact_percentile(xs, 1.0) == 100.0
