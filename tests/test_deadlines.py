"""Deadline hierarchy, abandonment reaping, stuck-task watchdog (PR 4).

Three authorities keep a query from hanging the cluster, each with its
own tests here:

  - the coordinator QueryTracker (runtime/query_tracker.py) enforces the
    planning/execution/run/CPU budget hierarchy and latches TYPED,
    NON-RETRYABLE errors — fake-clock unit tests pin which limit fires
    in which phase, and integration tests prove neither QUERY retry nor
    FTE task retry resubmits a killed query;
  - the server-side abandonment reaper cancels a query whose client
    stopped polling, with the resource-group slot and the memory-pool
    ledger both verified drained;
  - the worker stuck-task watchdog interrupts a wedged task with a
    diagnostic naming the stuck operator — and that failure IS
    retryable (the hung split may succeed elsewhere).
"""

import signal
import threading
import time

import pytest

from tests.oracle import assert_rows_match, sqlite_rows
from tests.test_tpch import to_sqlite
from trino_tpu.connectors.file import create_file_connector
from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import Session
from trino_tpu.runtime import DistributedQueryRunner, Worker
from trino_tpu.runtime.chaos import TIMEBOUND_CLASSES, ChaosHarness
from trino_tpu.runtime.failure import FailureInjector
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_CPU_LIMIT,
    EXCEEDED_TIME_LIMIT,
    EXECUTING,
    PLANNING,
    DeadlineLimits,
    ExceededCpuLimitError,
    ExceededTimeLimitError,
    QueryDeadlineError,
    QueryTracker,
    deadline_code,
    deadline_error,
)
from trino_tpu.runtime.worker import install_sigterm_self_drain

SF = 0.01
SEED = 42

Q_AGG = (
    "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)
Q_JOIN = (
    "select n_name, count(*) c from supplier, nation "
    "where s_nationkey = n_nationkey "
    "group by n_name order by n_name"
)


@pytest.fixture(scope="module")
def oracle():
    import sqlite3

    from tests.oracle import load_tpch_sqlite

    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


# -- QueryTracker unit tests (fake clock, explicit ticks) -------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _tracker():
    clock = FakeClock()
    return QueryTracker(clock=clock), clock


def test_run_time_limit_covers_queued_phase():
    """query_max_run_time_s counts from submission, so a query stuck in
    the admission queue burns budget and dies there — no phase is
    exempt."""
    tracker, clock = _tracker()
    kills = []
    tracker.register(
        "q1", DeadlineLimits(max_run_time_s=10.0), kill=kills.append
    )  # default phase: QUEUED
    clock.t = 9.0
    assert tracker.tick() == []
    clock.t = 10.5
    assert tracker.tick() == [("q1", EXCEEDED_TIME_LIMIT)]
    assert len(kills) == 1 and EXCEEDED_TIME_LIMIT in kills[0]
    # the kill latches: later ticks do not re-fire, check() raises it
    clock.t = 20.0
    assert tracker.tick() == []
    assert len(kills) == 1
    with pytest.raises(ExceededTimeLimitError):
        tracker.check("q1")


def test_planning_limit_fires_only_while_planning():
    tracker, clock = _tracker()
    limits = DeadlineLimits(max_planning_time_s=5.0)
    tracker.register("fast", limits, phase=PLANNING)
    tracker.register("slow", limits, phase=PLANNING)
    clock.t = 1.0
    tracker.transition("fast", EXECUTING)  # planned within budget
    clock.t = 6.0
    # "fast" left planning in time; only "slow" is still planning
    assert tracker.tick() == [("slow", EXCEEDED_TIME_LIMIT)]
    with pytest.raises(ExceededTimeLimitError):
        tracker.check("slow")
    tracker.check("fast")  # no latched error


def test_execution_limit_excludes_queue_and_planning_time():
    """The execution clock starts at the EXECUTING transition — time
    spent queued or planning must not count against it."""
    tracker, clock = _tracker()
    tracker.register("q1", DeadlineLimits(max_execution_time_s=5.0),
                     phase=PLANNING)
    clock.t = 10.0  # ten seconds of planning: not execution time
    tracker.transition("q1", EXECUTING)
    clock.t = 14.9
    assert tracker.tick() == []
    clock.t = 15.1
    assert tracker.tick() == [("q1", EXCEEDED_TIME_LIMIT)]


def test_cpu_limit_reads_the_task_ledger():
    tracker, clock = _tracker()
    cpu = [0.0]
    tracker.register(
        "q1",
        DeadlineLimits(max_cpu_time_s=1.0),
        cpu_time_fn=lambda: cpu[0],
        phase=EXECUTING,
    )
    clock.t = 100.0  # wall time is irrelevant to the CPU budget
    assert tracker.tick() == []
    cpu[0] = 1.5
    assert tracker.tick() == [("q1", EXCEEDED_CPU_LIMIT)]
    with pytest.raises(ExceededCpuLimitError):
        tracker.check("q1")


def test_completed_query_is_not_enforced():
    tracker, clock = _tracker()
    tracker.register("q1", DeadlineLimits(max_run_time_s=1.0))
    tracker.complete("q1")
    clock.t = 50.0
    assert tracker.tick() == []
    tracker.check("q1")  # unknown/completed queries never raise


def test_deadline_code_survives_stringly_propagation():
    """A kill message embeds its code in brackets; any layer that only
    sees the string (task failure, HTTP 500 body) can re-type it."""
    msg = f"Query q7 exceeded ... [{EXCEEDED_CPU_LIMIT}]"
    assert deadline_code(msg) == EXCEEDED_CPU_LIMIT
    assert deadline_code("task crashed: ordinary failure") is None
    assert deadline_code(None) is None
    err = deadline_error(msg)
    assert isinstance(err, ExceededCpuLimitError)
    assert isinstance(
        deadline_error(f"x [{EXCEEDED_TIME_LIMIT}]"), ExceededTimeLimitError
    )
    # non-retryable by construction: retry layers key off this flag
    assert QueryDeadlineError.retryable is False
    assert err.retryable is False


def test_limits_from_session():
    s = Session(catalog="tpch", schema="tiny",
                query_max_execution_time_s=2.5, query_max_cpu_time_s=1.0)
    limits = DeadlineLimits.from_session(s)
    assert limits.max_execution_time_s == 2.5
    assert limits.max_cpu_time_s == 1.0
    assert limits.max_planning_time_s == 0.0
    assert limits.any()
    assert not DeadlineLimits.from_session(
        Session(catalog="tpch", schema="tiny")
    ).any()


# -- integration: deadline kills are terminal, not retried ------------------


def _cluster(n_workers=2, **session_kw):
    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [
        Worker(f"dl-w{i}", cats, failure_injector=inj)
        for i in range(n_workers)
    ]
    runner = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", **session_kw),
        worker_handles=workers, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())
    return inj, runner


def test_execution_limit_kills_stalled_query_and_is_not_retried():
    """A batch-site stall with max_hits=1 would be absorbed by one
    whole-query retry (the replay runs clean) — so a successful result
    would prove the deadline error was WRONGLY retried. The correct
    behaviour: the tracker kills attempt 1, the coordinator re-types
    the failure, and QUERY retry refuses to resubmit."""
    inj, runner = _cluster(
        retry_policy="query", query_retry_count=5,
        query_max_execution_time_s=0.2,
    )
    inj.inject(where="batch", attempts=(0, 1, 2, 3), stall_s=30.0,
               max_hits=1)
    t0 = time.monotonic()
    try:
        with pytest.raises(ExceededTimeLimitError) as ei:
            runner.execute(Q_AGG)
    finally:
        inj.clear()
    assert EXCEEDED_TIME_LIMIT in str(ei.value)
    assert runner.last_query_attempts == 1, "deadline kill was resubmitted"
    # the kill must also unwedge the stalled task: nowhere near the
    # 30s stall, even on a slow box
    assert time.monotonic() - t0 < 15.0


def test_generic_crash_is_still_retried_under_query_policy(oracle):
    """Contrast case: an ordinary task crash (no deadline code) keeps
    its retryable classification and QUERY retry absorbs it."""
    inj, runner = _cluster(retry_policy="query", query_retry_count=3)
    inj.inject(where="start", fragment_id=0, partition=0,
               attempts=(0, 1, 2, 3), max_hits=1)
    try:
        rows = runner.execute(Q_JOIN).rows
    finally:
        inj.clear()
    assert_rows_match(
        rows, sqlite_rows(oracle, to_sqlite(Q_JOIN)),
        ordered=True, abs_tol=1e-2,
    )
    assert runner.last_query_attempts == 2


def test_cpu_limit_kills_via_task_cpu_ledger():
    """query_max_cpu_time_s aggregates worker-side thread_time ledgers
    (task_state "cpu_s"). Any real scan burns more than a microsecond,
    so a 1µs budget must die with the CPU-coded error — while the stall
    holds the query open long enough for the tracker to tick."""
    inj, runner = _cluster(
        retry_policy="query", query_retry_count=3,
        query_max_cpu_time_s=1e-6,
    )
    inj.inject(where="batch", attempts=(0, 1, 2, 3), stall_s=30.0,
               max_hits=1)
    try:
        with pytest.raises(ExceededCpuLimitError) as ei:
            runner.execute(Q_AGG)
    finally:
        inj.clear()
    assert EXCEEDED_CPU_LIMIT in str(ei.value)
    assert runner.last_query_attempts == 1


def test_fte_does_not_retry_deadline_kills():
    """Same non-retry contract on the FTE path: task retry absorbs
    ordinary failures (max_hits=1 would succeed on replay) but must
    surface a deadline-coded kill immediately."""
    inj, runner = _cluster(
        retry_policy="task", task_retries=3,
        query_max_execution_time_s=0.2,
    )
    inj.inject(where="batch", attempts=(0, 1, 2, 3), stall_s=30.0,
               max_hits=1)
    try:
        with pytest.raises(ExceededTimeLimitError):
            runner.execute(Q_AGG)
    finally:
        inj.clear()


# -- abandonment reaping ----------------------------------------------------


def _timebound_harness() -> ChaosHarness:
    h = ChaosHarness(
        n_workers=3,
        stuck_task_interrupt_s=1.0,
        memory_pool_bytes=256 << 20,
    )
    h.register_catalog("tpch", create_tpch_connector())
    return h


def test_abandoned_client_is_reaped_slot_and_memory_drained():
    """A client that submits and never polls: the reaper cancels the
    query, the resource-group slot goes back (total_running == 0) and
    every worker memory pool's per-query ledger drains to zero."""
    _, report = _timebound_harness().run_abandoned_client_case(
        Q_AGG, seed=SEED
    )
    assert report["reaped"], report
    assert "abandoned" in (report["error"] or "").lower(), report
    assert report["rg_running"] == 0, "resource-group slot leaked"
    assert not any(report["ledgers"].values()), (
        f"memory ledger not drained: {report['ledgers']}"
    )


# -- stuck-task watchdog ----------------------------------------------------


def test_watchdog_interrupts_hung_operator_and_names_it(oracle):
    """A wedged batch (hung operator) is interrupted by the worker
    watchdog with a diagnostic naming the stuck operator and the last
    batch timestamp; the failure is RETRYABLE, so FTE re-runs the task
    and the query still answers correctly — well before the stall would
    have expired on its own."""
    h = _timebound_harness()
    rows, report = h.run_hung_operator_case(Q_AGG, seed=SEED)
    assert_rows_match(
        rows, sqlite_rows(oracle, to_sqlite(Q_AGG)),
        ordered=True, abs_tol=1e-2,
    )
    interrupts = report["watchdog_interrupts"]
    assert interrupts, "watchdog never fired"
    assert any("Stuck task" in d for d in interrupts), interrupts
    assert any("in operator" in d for d in interrupts), (
        f"diagnostic does not name the operator: {interrupts}"
    )
    assert any("last batch at t=" in d for d in interrupts), interrupts
    # un-wedged proof: recovery overhead (elapsed beyond the warm clean
    # baseline the case measured itself) stays under the stall — only a
    # broken watchdog waits out the injected stall in full
    overhead = report["elapsed_s"] - report["warm_clean_s"]
    assert overhead < report["stall_s"], (
        f"query waited out the stall (overhead {overhead:.2f}s) — "
        f"the watchdog did not unwedge it"
    )


def test_watchdog_does_not_fire_on_healthy_tasks():
    """A worker whose tasks make progress never trips the watchdog:
    watchdog_once on an idle/healthy worker reports nothing."""
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    w = Worker("wd-w0", cats, stuck_task_interrupt_s=1.0)
    runner = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny"), worker_handles=[w],
    )
    runner.register_catalog("tpch", create_tpch_connector())
    assert runner.execute("select count(*) from nation").rows == [[25]]
    assert w.watchdog_once() == []
    assert w.watchdog_interrupts == []


# -- worker SIGTERM self-drain ----------------------------------------------


def test_sigterm_drains_all_workers():
    """SIGTERM routes into graceful drain: every registered worker flips
    to SHUTTING_DOWN (new launches refused) instead of dying mid-task.
    The handler is invoked directly — sending a real signal to the test
    process would race pytest's own machinery."""
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [Worker(f"sig-w{i}", cats) for i in range(2)]
    prev = install_sigterm_self_drain(workers)
    try:
        handler = signal.getsignal(signal.SIGTERM)
        assert callable(handler)
        handler(signal.SIGTERM, None)
        assert all(w.state == "shutting_down" for w in workers)
        from trino_tpu.runtime.worker import WorkerShuttingDownError
        from trino_tpu.runtime.task import TaskSpec

        with pytest.raises(WorkerShuttingDownError):
            workers[0].create_task(
                TaskSpec(
                    task_id="sig-q0.0.0", fragment=None,
                    n_output_partitions=1, remote_schemas={},
                    scan_slice=None, input_locations={},
                )
            )
    finally:
        if prev is not None:
            signal.signal(signal.SIGTERM, prev)


# -- split-listing invalidation between QUERY attempts ----------------------


def test_query_retry_invalidates_split_listings(tmp_path, oracle):
    """A whole-query replay must not trust connector split caches from
    the failed attempt (files may have changed underneath a cached
    parse): each retry boundary calls invalidate_split_listings, visible
    as the FileSplitManager invalidation counter ticking."""
    data = tmp_path / "shop" / "sales.csv"
    data.parent.mkdir(parents=True)
    data.write_text(
        "region,units\n"
        "east,3\n"
        "west,5\n"
        "east,2\n"
    )
    file_conn = create_file_connector(str(tmp_path))
    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("files", file_conn)
    workers = [
        Worker(f"inv-w{i}", cats, failure_injector=inj) for i in range(2)
    ]
    runner = DistributedQueryRunner(
        Session(catalog="files", schema="shop", retry_policy="query",
                query_retry_count=3),
        worker_handles=workers, hash_partitions=2,
    )
    runner.register_catalog("files", file_conn)
    sm = file_conn.split_manager
    assert sm.invalidations == 0
    inj.inject(where="start", fragment_id=0, partition=0,
               attempts=(0, 1, 2, 3), max_hits=1)
    try:
        rows = runner.execute(
            "select region, sum(units) from sales "
            "group by region order by region"
        ).rows
    finally:
        inj.clear()
    assert rows == [["east", 5], ["west", 5]]
    assert runner.last_query_attempts == 2
    assert sm.invalidations >= 1, (
        "retry attempt reused the failed attempt's split listings"
    )


# -- p75 speculation threshold ----------------------------------------------


def test_quantile_interpolation():
    from trino_tpu.runtime.fte import _quantile

    vals = [1.0, 2.0, 3.0, 4.0]
    assert _quantile(vals, 0.5) == pytest.approx(2.5)
    assert _quantile(vals, 0.75) == pytest.approx(3.25)
    assert _quantile(vals, 1.0) == pytest.approx(4.0)
    assert _quantile([7.0], 0.75) == pytest.approx(7.0)


def test_fte_stats_surface_speculation_percentile():
    """The straggler threshold is a per-fragment p75 of committed wall
    times (session-tunable via speculation_percentile) and the quantile
    used is surfaced in last_fte_stats."""
    _, runner = _cluster(retry_policy="task", task_retries=2)
    runner.execute(Q_AGG)
    stats = runner.last_fte_stats
    assert stats["speculation_percentile"] == pytest.approx(0.75)
    assert "speculation_estimates" in stats

    _, runner9 = _cluster(retry_policy="task", task_retries=2,
                          speculation_percentile=0.9)
    runner9.execute(Q_AGG)
    assert runner9.last_fte_stats["speculation_percentile"] == (
        pytest.approx(0.9)
    )


# -- slow soak: the timebound chaos classes over several seeds --------------


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("scenario", TIMEBOUND_CLASSES)
def test_timebound_soak(scenario, seed, oracle):
    h = _timebound_harness()
    if scenario == "hung_operator":
        rows, report = h.run_hung_operator_case(Q_AGG, seed=seed)
        assert_rows_match(
            rows, sqlite_rows(oracle, to_sqlite(Q_AGG)),
            ordered=True, abs_tol=1e-2,
        )
        assert report["watchdog_interrupts"], report
        overhead = report["elapsed_s"] - report["warm_clean_s"]
        assert overhead < report["stall_s"], report
    else:
        h.run_clean(Q_AGG)  # warm generation caches before the stall
        _, report = h.run_abandoned_client_case(Q_AGG, seed=seed)
        assert report["reaped"], report
        assert report["rg_running"] == 0, report
        assert not any(report["ledgers"].values()), report
