"""Benchmark entry point — prints ONE JSON line.

Metric: TPC-H Q1 wall-clock through the full SQL engine (parse ->
analyze -> plan -> jitted device pipeline) on tpch.sf1, steady state
(compile excluded; Trino's benchto methodology of prewarm + repeat runs,
SURVEY.md §6). `vs_baseline` is the speedup of the default device
(the TPU chip under the driver) over this host's CPU backend running
the identical engine, measured in a subprocess — the reference
publishes no absolute numbers (BASELINE.md), so the CPU path of the
same columnar engine is the comparison point.

Env knobs: BENCH_SF (default 1), BENCH_RUNS (default 3),
BENCH_SKIP_CPU=1 to skip the CPU-subprocess baseline.

Measurement note: over a tunneled device link the wall-clock floor is
ONE host<->device round trip (~110ms measured) for result delivery —
at SF1 the device compute is <1ms, so vs_baseline ~1 against the CPU
engine is the RTT floor, not kernel speed (measured identically at
SF10: 0.148s device wall for 60M rows). Kernel-level speed lives in
benchmarks/micro.py (e.g. Pallas MXU group-by 625 Mrows/s vs 9 on the
sort path; join probe 85 Mrows/s after the sort-merge rewrite).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

SF = float(os.environ.get("BENCH_SF", "1"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))

Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
  sum(l_extendedprice) as sum_base_price,
  sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
  sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
  avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
  avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""


Q1_COLUMNS = [
    "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
    "l_discount", "l_tax", "l_shipdate",
]


def run_bench() -> float:
    """Median steady-state Q1 wall-clock in seconds on this process's
    default jax platform. lineitem is pre-loaded into the memory
    connector (device-resident after the prewarm scan) so the metric is
    the query engine, not the data generator."""
    from trino_tpu.connectors.memory import create_memory_connector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.connectors.tpch import TABLES, base_row_count, generate_column
    from trino_tpu.engine import LocalQueryRunner, Session

    mem = create_memory_connector()
    types = dict(TABLES["lineitem"])
    base = base_row_count("lineitem", SF)
    arrays, dicts = [], []
    for name in Q1_COLUMNS:
        data, d = generate_column("lineitem", name, SF, 0, base)
        arrays.append(data)
        dicts.append(d)
    mem.load_table(
        "bench", "lineitem",
        [ColumnMetadata(n, types[n]) for n in Q1_COLUMNS],
        arrays, None, dicts,
    )

    r = LocalQueryRunner(Session(catalog="memory", schema="bench"))
    r.register_catalog("memory", mem)

    rows = r.execute(Q1).rows  # prewarm: host->device + compile
    assert len(rows) == 4, rows
    times = []
    for _ in range(RUNS):
        t0 = time.perf_counter()
        r.execute(Q1)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def main() -> None:
    if os.environ.get("BENCH_INNER") == "1":
        print(json.dumps({"seconds": run_bench()}))
        return

    import jax

    device_time = run_bench()
    platform = jax.devices()[0].platform

    vs_baseline = 1.0
    if platform != "cpu" and os.environ.get("BENCH_SKIP_CPU") != "1":
        env = dict(os.environ, BENCH_INNER="1", JAX_PLATFORMS="cpu")
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                capture_output=True,
                text=True,
                timeout=1800,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            cpu_time = json.loads(out.stdout.strip().splitlines()[-1])["seconds"]
            vs_baseline = cpu_time / device_time
        except Exception:
            vs_baseline = 1.0

    print(
        json.dumps(
            {
                "metric": f"tpch_sf{SF:g}_q1_wall",
                "value": round(device_time, 4),
                "unit": "s",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
