"""Benchmark entry point — prints ONE JSON line.

North-star configs (BASELINE.md): TPC-H Q3 (SF1/SF10) and Q18 (SF10)
wall-clock through the full SQL engine (parse -> analyze -> plan ->
jitted device pipeline), steady state (prewarm + repeat, the benchto
methodology, SURVEY.md §6), plus hash-probe GB/s per chip. Headline
metric = Q18 SF10 (large-state aggregation + semi-join, BASELINE
config 3); the other measurements ride in "extra".

`vs_baseline` is the speedup of the default device (the TPU chip under
the driver) over this host's CPU backend running the IDENTICAL engine
in a subprocess — the reference publishes no absolute numbers
(BASELINE.md), so the same engine's CPU path is the comparison point,
standing in for the "32-vCPU Java worker" of the north star.

Env knobs:
  BENCH_FAST=1     -> only Q1 SF1 (smoke)
  BENCH_RUNS=N     -> steady-state repetitions (default 3)
  BENCH_SKIP_CPU=1 -> skip the CPU-subprocess baseline
  BENCH_SF_LARGE=N -> scale factor for the large configs (default 10)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

RUNS = int(os.environ.get("BENCH_RUNS", "3"))
SF_LARGE = float(os.environ.get("BENCH_SF_LARGE", "10"))
FAST = os.environ.get("BENCH_FAST") == "1"
if "BENCH_SF" in os.environ:  # pre-r2 knob: map onto the large configs
    print(
        "bench.py: BENCH_SF is superseded by BENCH_SF_LARGE; honoring it",
        file=sys.stderr,
    )
    SF_LARGE = float(os.environ["BENCH_SF"])

Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
  sum(l_extendedprice) as sum_base_price,
  sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
  sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
  avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
  avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
  o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
"""

Q18 = """
select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
  sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (
    select l_orderkey from lineitem group by l_orderkey
    having sum(l_quantity) > 300)
  and c_custkey = o_custkey and o_orderkey = l_orderkey
group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
order by o_totalprice desc, o_orderdate
limit 100
"""

# columns each config needs resident (pruned load keeps host+device RAM
# proportional to what the queries touch)
TABLE_COLUMNS = {
    "q1": {
        "lineitem": [
            "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate",
        ],
    },
    "q3": {
        "customer": ["c_custkey", "c_mktsegment"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"],
        "lineitem": ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"],
    },
    "q18": {
        "customer": ["c_custkey", "c_name"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_totalprice"],
        "lineitem": ["l_orderkey", "l_quantity"],
    },
}
SQL = {"q1": Q1, "q3": Q3, "q18": Q18}


def _make_runner(sf: float, table_columns):
    """LocalQueryRunner over the memory connector with the needed
    columns preloaded (device-resident after the prewarm scan)."""
    from trino_tpu.connectors.memory import create_memory_connector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.connectors.tpch import TABLES, base_row_count, generate_column
    from trino_tpu.engine import LocalQueryRunner, Session

    mem = create_memory_connector()
    for table, cols in table_columns.items():
        types = dict(TABLES[table])
        base = base_row_count(table, sf)
        arrays, dicts = [], []
        for name in cols:
            data, d = generate_column(table, name, sf, 0, base)
            arrays.append(data)
            dicts.append(d)
        mem.load_table(
            "bench", table,
            [ColumnMetadata(n, types[n]) for n in cols],
            arrays, None, dicts,
        )
    r = LocalQueryRunner(Session(catalog="memory", schema="bench"))
    r.register_catalog("memory", mem)
    return r


def _median_wall(runner, sql: str, runs: int = RUNS) -> float:
    runner.execute(sql)  # prewarm: host->device + compile
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        runner.execute(sql)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _configs():
    only = os.environ.get("BENCH_ONLY")
    if only:
        name, sf = only.split(":")
        return [(name, float(sf))]
    if FAST:
        return [("q1", 1.0)]
    return [("q1", 1.0), ("q3", 1.0), ("q3", SF_LARGE), ("q18", SF_LARGE)]


def run_benches() -> dict:
    """All configs on this process's default jax platform. Returns
    {metric_name: seconds}. Runners are built per (sf, union-of-columns)
    so the two SF-large configs share one generation pass per table."""
    out = {}
    by_sf = {}
    for name, sf in _configs():
        by_sf.setdefault(sf, {})
        for table, cols in TABLE_COLUMNS[name].items():
            cur = by_sf[sf].setdefault(table, [])
            for c in cols:
                if c not in cur:
                    cur.append(c)
    runners = {}
    for sf, tables in by_sf.items():
        print(f"bench: generating sf={sf:g} tables...", file=sys.stderr, flush=True)
        runners[sf] = _make_runner(sf, tables)
    for name, sf in _configs():
        # SF-large configs trim one run, but never EXCEED the requested
        # count (the CPU baseline passes BENCH_RUNS=1 and means it)
        runs = RUNS if sf <= 1 else min(RUNS, max(2, RUNS - 1))
        print(f"bench: running {name} sf={sf:g}...", file=sys.stderr, flush=True)
        t0 = time.time()
        out[f"{name}_sf{sf:g}"] = round(
            _median_wall(runners[sf], SQL[name], runs), 4
        )
        print(
            f"bench: {name} sf={sf:g} wall={out[f'{name}_sf{sf:g}']}s "
            f"(total {time.time()-t0:.0f}s incl. prewarm)",
            file=sys.stderr, flush=True,
        )
    return out


PROBE_ROWS = 1_000_000


def probe_gbs(n: int = PROBE_ROWS) -> float:
    """Hash-probe throughput in GB/s of probe-side key bytes (the
    BASELINE.json 'hash-probe GB/s per chip' metric). n matches
    benchmarks/micro.py's join_probe shape so the compile is already
    cached; the slope-based _measure amortizes dispatch overhead, and
    the reported number carries its row count in `extra` so readings at
    different n are not silently compared."""
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.micro import _measure
    from trino_tpu.ops import join as J

    rng = np.random.default_rng(0)
    build_n = n // 8
    bkeys = [jnp.asarray(np.arange(build_n, dtype=np.int64))]
    bvalids = [jnp.ones(build_n, dtype=jnp.bool_)]
    lookup = J.build_lookup(bkeys, bvalids, jnp.ones(build_n, dtype=jnp.bool_))
    pkeys = [jnp.asarray(rng.integers(0, build_n * 2, n).astype(np.int64))]
    pvalids = [jnp.ones(n, dtype=jnp.bool_)]
    plive = jnp.ones(n, dtype=jnp.bool_)

    def run():
        return J.probe_counts(lookup, pkeys, pvalids, plive)

    secs = _measure(run)
    return round(n * 8 / secs / 1e9, 2)


def _run_one_subprocess(name: str, sf: float, platform_env: dict,
                        timeout_s: int):
    """One config in an isolated subprocess (a first-compile that runs
    away must never wedge the whole bench — the driver runs this
    un-supervised at round end). Returns seconds or None."""
    env = dict(os.environ, BENCH_INNER="1", BENCH_ONLY=f"{name}:{sf:g}")
    env.update(platform_env)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in out.stderr.splitlines():
            if line.startswith("bench:"):
                print(line, file=sys.stderr, flush=True)
        if not out.stdout.strip():
            # inner crash: surface the traceback tail, not an IndexError
            for line in out.stderr.splitlines()[-15:]:
                print(f"bench[inner]: {line}", file=sys.stderr, flush=True)
            print(
                f"bench: {name} sf={sf:g} inner exited rc={out.returncode}"
                " with no result",
                file=sys.stderr, flush=True,
            )
            return None
        return json.loads(out.stdout.strip().splitlines()[-1])[
            f"{name}_sf{sf:g}"
        ]
    except subprocess.TimeoutExpired as ex:
        err = ex.stderr or b""
        if isinstance(err, bytes):  # communicate() yields bytes on timeout
            err = err.decode("utf-8", "replace")
        for line in err.splitlines():
            if line.startswith("bench:"):
                print(line, file=sys.stderr, flush=True)
        print(f"bench: {name} sf={sf:g} skipped (TimeoutExpired)",
              file=sys.stderr, flush=True)
        return None
    except Exception as ex:
        print(f"bench: {name} sf={sf:g} skipped ({type(ex).__name__})",
              file=sys.stderr, flush=True)
        return None


def main() -> None:
    if os.environ.get("BENCH_INNER") == "1":
        print(json.dumps(run_benches()))
        return

    # device configs run FIRST, before this process touches jax: a
    # parent holding the TPU could wedge children on device-exclusive
    # backends
    device: dict = {}
    for name, sf in _configs():
        secs = _run_one_subprocess(
            name, sf, {}, int(os.environ.get("BENCH_CONFIG_TIMEOUT", "1800"))
        )
        if secs is not None:
            device[f"{name}_sf{sf:g}"] = secs

    import jax

    platform = jax.devices()[0].platform
    gbs = probe_gbs() if platform != "cpu" else None

    baseline = {}
    if platform != "cpu" and os.environ.get("BENCH_SKIP_CPU") != "1":
        # one baseline run per config: the CPU engine at SF10 is minutes
        # per execution and the comparison needs one honest number
        for name, sf in _configs():
            key = f"{name}_sf{sf:g}"
            if key not in device:
                continue
            secs = _run_one_subprocess(
                name, sf,
                {"JAX_PLATFORMS": "cpu", "BENCH_RUNS": "1"},
                int(os.environ.get("BENCH_CPU_TIMEOUT", "1800")),
            )
            if secs is not None:
                baseline[key] = secs

    extra = {}
    for k, v in device.items():
        extra[k] = {"wall_s": v}
        if k in baseline:
            extra[k]["cpu_s"] = baseline[k]
            extra[k]["vs_cpu"] = round(baseline[k] / v, 3)
    if gbs is not None:
        extra["hash_probe"] = {"gb_s": gbs, "rows": PROBE_ROWS}

    if not device:
        # even total failure must emit the driver's one JSON line
        print(
            json.dumps(
                {"metric": "bench_failed", "value": 0.0, "unit": "s",
                 "vs_baseline": 0.0, "extra": {}}
            )
        )
        return
    # headline: the largest completed north-star config, preferring one
    # whose CPU baseline actually completed (a missing comparison must
    # not masquerade as a measured 1.0x)
    order = [f"q18_sf{SF_LARGE:g}", f"q3_sf{SF_LARGE:g}", "q3_sf1", "q1_sf1"]
    with_vs = [k for k in order if k in device and "vs_cpu" in extra[k]]
    candidates = with_vs or [k for k in order if k in device] or sorted(device)
    headline = candidates[0]
    value = device[headline]
    vs = extra[headline].get("vs_cpu", 1.0)
    if "vs_cpu" not in extra[headline]:
        extra["note"] = "cpu baseline missing for headline; vs_baseline unmeasured"
    else:
        # demotion must be loud: a larger config completed on device but
        # lost its CPU baseline, so the headline metric name changed
        passed_over = [
            k for k in order[: order.index(headline)] if k in device
        ]
        if passed_over:
            extra["note"] = (
                f"headline demoted to {headline}; completed without cpu "
                f"baseline: {', '.join(passed_over)}"
            )
    print(
        json.dumps(
            {
                "metric": f"tpch_{headline}_wall",
                "value": value,
                "unit": "s",
                "vs_baseline": vs,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
