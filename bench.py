"""Benchmark entry point — prints ONE JSON line.

North-star configs (BASELINE.md): TPC-H Q3 (SF1/SF10) and Q18 (SF10)
wall-clock through the full SQL engine (parse -> analyze -> plan ->
jitted device pipeline), steady state (prewarm + repeat, the benchto
methodology, SURVEY.md §6), plus hash-probe GB/s per chip. Headline
metric = Q18 SF10 (large-state aggregation + semi-join, BASELINE
config 3); the other measurements ride in "extra".

`vs_baseline` is the speedup of the default device (the TPU chip under
the driver) over this host's CPU backend running the IDENTICAL engine
in a subprocess — the reference publishes no absolute numbers
(BASELINE.md), so the same engine's CPU path is the comparison point,
standing in for the "32-vCPU Java worker" of the north star.

The headline JSON line is re-emitted after EVERY completed config, so
the last stdout line is always the best complete result no matter when
the process is killed (the driver runs this under a hard timeout; a
bench that loses finished measurements to a later config's overrun
ships nothing).

Env knobs:
  BENCH_FAST=1     -> only Q1 SF1 (smoke)
  BENCH_RUNS=N     -> steady-state repetitions (default 3)
  BENCH_SKIP_CPU=1 -> skip the CPU-subprocess baseline
  BENCH_SF_LARGE=N -> scale factor for the large configs (default 10)
  BENCH_DEADLINE=N -> global wall budget in seconds (default 2700);
                      remaining configs are skipped when short, SF-large
                      CPU baselines first (the driver's own timeout can
                      land anytime — the last emitted line always holds
                      the best complete result)
  BENCH_WIDESTR_ROWS=N -> rows for the wide-string GROUP BY config

Flags:
  --watch [seconds]  dev-loop mode: re-run the device preflight every
                     `seconds` (default 300) until a non-CPU backend
                     initializes, then run the full bench once; device
                     walls append to BENCH_DEV.json as usual
  --chaos-smoke [seed]  run the seeded chaos harness (runtime/chaos.py)
                     over representative TPC-H shapes under every fault
                     class, the lifecycle maneuvers, and the timebound
                     scenarios (hung operator vs the stuck-task
                     watchdog, abandoned client vs the reaper); exits
                     non-zero if any run diverges from the clean answer,
                     exceeds its injected-failure bound, leaks a
                     resource-group slot, or leaves memory reserved; no
                     device needed (runs before preflight)
  --warmup-smoke     run the q72-class plan cold-with-warmup vs
                     cold-without (compile/warmup.py) and print per-arm
                     compile counts + walls; exits non-zero if the
                     warmup-on run observes more distinct XLA shape
                     classes than the census predicted; no device
                     needed (runs before preflight)
  --trace-smoke      run a traced distributed TPC-H query plus one
                     chaos scenario (runtime/tracing.py), validate the
                     exported span tree and Chrome trace-event schema,
                     and measure tracing overhead on the Q1/Q6 pair;
                     exits non-zero on an invariant violation or >5%
                     wall overhead; no device needed (runs before
                     preflight)
  --mesh-smoke       run Q1/Q6 plus a hash join chunked over an
                     8-device CPU mesh (parallel/mesh_chunk.py):
                     answer-equality vs the page plane, >=1 all_to_all,
                     zero new XLA lowerings on second execution, and a
                     mid-query deadline kill preempting between chunks
                     with the typed EXCEEDED_TIME_LIMIT error and no
                     page fallback; re-execs itself with an 8-device
                     host platform, so no device needed
  --resident-smoke   exercise the resident state tier
                     (trino_tpu/resident/): warm point-lookup p50 at
                     device-probe latency (faster than the cold path,
                     resident.hits > 0, zero rebuilds), oracle-equality
                     through DML invalidation, the delta-append path
                     and background compaction, zero post-warmup XLA
                     lowerings for repeated pinned probes, and graceful
                     cold-path degradation under a zero pin budget; no
                     device needed (runs before preflight)
  --adaptive-smoke   exercise the adaptive execution tier
                     (trino_tpu/adaptive/): a q72-class join over
                     deliberately misestimated stats, two arms on the
                     same lying catalog; the adaptive arm must re-plan
                     >=1 time, stay oracle-equal with the non-adaptive
                     arm, beat its warm wall, and mint zero new XLA
                     lowerings in the warm loop; JSON re-plan counts,
                     exit 1 on violation; no device needed (runs before
                     preflight)
  --recovery-smoke   exercise the recovery tier (trino_tpu/recovery/):
                     a q72-class deep join chunked over an 8-device CPU
                     mesh takes an injected device loss at chunk k of K
                     twice — once with checkpointing off (the fault
                     discards every completed chunk and the page plane
                     recomputes from zero) and once with chunk
                     checkpointing on (the run resumes from the last
                     checkpoint); the resumed arm must stay oracle-
                     equal, re-execute fewer chunks than the restart,
                     beat the restart wall, and mint zero new XLA
                     lowerings; re-execs itself with an 8-device host
                     platform, so no device needed
  --skew-smoke       exercise the skew-aware join plane: a zipf-skewed
                     join whose build barrier detects the heavy hitter
                     from observed stats and salts the mesh exchange
                     (oracle-equal, salted counters advance, zero new
                     lowerings warm), plus a high-fanout join-aggregate
                     lowered to the MXU join-project kernel (oracle-
                     equal vs the gather path, beats its warm wall);
                     re-execs itself with an 8-device host platform,
                     so no device needed
  --preempt-smoke    exercise checkpoint-backed preemptive
                     multi-tenancy (runtime/scheduler.py): point-
                     lookup p99 under a streaming q72-class analytic
                     must stay within 5x the solo p99 (the fast lane
                     preempts at chunk boundaries), a mid-analytic
                     arrival parks the device carries and resumes them
                     byte-identical with zero re-executed chunks and
                     zero new lowerings, and the park/resume wall must
                     beat abandoning + rerunning the analytic;
                     re-execs itself with an 8-device host platform,
                     so no device needed
  --multihost-smoke  exercise the multi-host replica fabric
                     (runtime/fabric.py) across a REAL process
                     boundary: a victim coordinator subprocess streams
                     its chunk checkpoints to the survivor's fabric
                     endpoint and hard-kills itself (os._exit) at
                     chunk 3K/4; the survivor digest-rejects a
                     corrupted replay, then resumes the query from
                     exactly the fault chunk — oracle-equal, zero
                     re-executed chunk-steps, zero new lowerings,
                     beating its own warm full-length wall;
                     re-execs itself with an 8-device host platform,
                     so no device needed
  --analyze          run the static concurrency analyzer
                     (trino_tpu/analysis/) over the whole package:
                     lock-order cycle detection on the may-hold-while-
                     acquiring graph, guarded_by annotation checking,
                     unlocked-global-write lint, and the unregistered-
                     thread-spawn lint; prints a JSON summary plus one
                     ANALYZE-VIOLATION line per finding at file:line;
                     exits non-zero on any finding; no device needed
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Sequence

RUNS = int(os.environ.get("BENCH_RUNS", "3"))
SF_LARGE = float(os.environ.get("BENCH_SF_LARGE", "10"))
FAST = os.environ.get("BENCH_FAST") == "1"
if "BENCH_SF" in os.environ:  # pre-r2 knob: map onto the large configs
    print(
        "bench.py: BENCH_SF is superseded by BENCH_SF_LARGE; honoring it",
        file=sys.stderr,
    )
    SF_LARGE = float(os.environ["BENCH_SF"])

Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
  sum(l_extendedprice) as sum_base_price,
  sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
  sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
  avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
  avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

# TPC-H Q6: the trace-smoke overhead pair partner to Q1 — a scan-heavy
# single-fragment aggregate where per-operator instrumentation cost has
# nowhere to hide behind join/shuffle work
Q6 = """
select sum(l_extendedprice * l_discount) as revenue from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
  o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
"""

Q18 = """
select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
  sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (
    select l_orderkey from lineitem group by l_orderkey
    having sum(l_quantity) > 300)
  and c_custkey = o_custkey and o_orderkey = l_orderkey
group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
order by o_totalprice desc, o_orderdate
limit 100
"""

# BASELINE config 4: TPC-DS q72 (deep multi-build join tree;
# partitioned lookup) — template matches tests/test_tpcds.py
Q72 = """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
  sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
  sum(case when p_promo_sk is not null then 1 else 0 end) promo,
  count(*) total_cnt
from catalog_sales
join inventory on (cs_item_sk = inv_item_sk)
join warehouse on (w_warehouse_sk = inv_warehouse_sk)
join item on (i_item_sk = cs_item_sk)
join customer_demographics on (cs_bill_cdemo_sk = cd_demo_sk)
join household_demographics on (cs_bill_hdemo_sk = hd_demo_sk)
join date_dim d1 on (cs_sold_date_sk = d1.d_date_sk)
join date_dim d2 on (inv_date_sk = d2.d_date_sk)
join date_dim d3 on (cs_ship_date_sk = d3.d_date_sk)
left outer join promotion on (cs_promo_sk = p_promo_sk)
left outer join catalog_returns on (cr_item_sk = cs_item_sk
                                    and cr_order_number = cs_order_number)
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + 5
  and hd_buy_potential = '>10000'
  and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq
limit 100
"""

# BASELINE config 5: synthetic wide-string GROUP BY (variable-width ->
# device dictionary encoding) over the memory connector
WIDESTR = """
select s, count(*) as cnt, sum(v) as total
from widestr group by s order by cnt desc, s limit 10
"""

WIDESTR_ROWS = int(os.environ.get("BENCH_WIDESTR_ROWS", str(1 << 21)))
WIDESTR_GROUPS = 512
WIDESTR_WIDTH = 64

# columns each config needs resident (pruned load keeps host+device RAM
# proportional to what the queries touch)
TABLE_COLUMNS = {
    "q1": {
        "lineitem": [
            "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate",
        ],
    },
    "q3": {
        "customer": ["c_custkey", "c_mktsegment"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"],
        "lineitem": ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"],
    },
    "q18": {
        "customer": ["c_custkey", "c_name"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_totalprice"],
        "lineitem": ["l_orderkey", "l_quantity"],
    },
}
SQL = {"q1": Q1, "q3": Q3, "q18": Q18, "q72": Q72, "widestr": WIDESTR}


_TABLE_CACHE_DIR = os.path.expanduser(
    os.environ.get("BENCH_TABLE_CACHE", "~/.trino_tpu_bench_cache")
)


def _cached_column(table: str, name: str, sf: float, base: int):
    """Generated TPC-H columns cached as .npz on disk: SF10 generation
    costs minutes per config SUBPROCESS (each config is isolated), which
    alone could blow the driver's bench budget. The generator is
    deterministic, so the cache is exact."""
    import numpy as np

    from trino_tpu.connectors.tpch import generate_column

    path = os.path.join(
        _TABLE_CACHE_DIR, f"{table}.{name}.sf{sf:g}.npz"
    )
    if os.path.exists(path):
        try:
            with np.load(path, allow_pickle=False) as z:
                data = z["data"]
                dvals = z["dict"] if "dict" in z.files else None
            if dvals is not None:
                from trino_tpu.block import Dictionary

                d = Dictionary([str(v) for v in dvals])
            else:
                d = None
            return data, d
        except Exception:
            pass  # corrupt cache entry: regenerate below
    data, d = generate_column(table, name, sf, 0, base)
    try:
        os.makedirs(_TABLE_CACHE_DIR, exist_ok=True)
        tmp = path + ".tmp.npz"  # savez keeps a name already ending .npz
        if d is not None:
            np.savez(tmp, data=data, dict=np.asarray(list(d.values)))
        else:
            np.savez(tmp, data=data)
        os.replace(tmp, path)
    except Exception:
        pass  # cache is an optimization only
    return data, d


def _make_runner(sf: float, table_columns):
    """LocalQueryRunner over the memory connector with the needed
    columns preloaded (device-resident after the prewarm scan)."""
    from trino_tpu.connectors.memory import create_memory_connector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.connectors.tpch import TABLES, base_row_count
    from trino_tpu.engine import LocalQueryRunner, Session

    mem = create_memory_connector()
    for table, cols in table_columns.items():
        types = dict(TABLES[table])
        base = base_row_count(table, sf)
        arrays, dicts = [], []
        for name in cols:
            data, d = _cached_column(table, name, sf, base)
            arrays.append(data)
            dicts.append(d)
        mem.load_table(
            "bench", table,
            [ColumnMetadata(n, types[n]) for n in cols],
            arrays, None, dicts,
        )
    # 4M-row batches beat the engine's 1M default on the tunneled
    # device: fewer dispatches amortize per-batch RTT (measured Q18
    # SF10 104s -> 62s, Q3 SF10 20.9s -> 11.0s); the dev loop prewarms
    # these shapes so driver runs hit a warm compile cache. The CPU
    # baseline subprocess pins its own batch size via _CPU_ENV.
    batch_rows = int(os.environ.get("BENCH_BATCH_ROWS", str(1 << 22)))
    r = LocalQueryRunner(
        Session(catalog="memory", schema="bench", batch_rows=batch_rows)
    )
    r.register_catalog("memory", mem)
    return r


def _median_wall(runner, sql: str, runs: int = RUNS) -> float:
    runner.execute(sql)  # prewarm: host->device + compile
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        runner.execute(sql)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _configs():
    only = os.environ.get("BENCH_ONLY")
    if only:
        name, sf = only.split(":")
        return [(name, float(sf))]
    if FAST:
        return [("q1", 1.0)]
    # q72/widestr (BASELINE configs 4-5) run LAST: the deadline logic
    # sheds them first, protecting the headline configs
    return [
        ("q1", 1.0), ("q3", 1.0), ("q3", SF_LARGE), ("q18", SF_LARGE),
        ("q72", SF_LARGE), ("widestr", 1.0),
    ]


def _make_tpcds_runner(sf: float):
    """LocalQueryRunner over the tpcds connector (BASELINE config 4).
    Generation is on-scan; the engine's plan cache snapshots splits, so
    steady-state repeats re-read generated pages, not re-plan."""
    from trino_tpu.connectors.tpcds import create_tpcds_connector
    from trino_tpu.engine import LocalQueryRunner, Session

    batch_rows = int(os.environ.get("BENCH_BATCH_ROWS", str(1 << 22)))
    r = LocalQueryRunner(
        Session(catalog="tpcds", schema=f"sf{sf:g}", batch_rows=batch_rows)
    )
    r.register_catalog("tpcds", create_tpcds_connector())
    return r


def _make_widestr_runner():
    """Memory-connector table for BASELINE config 5: wide dictionary
    strings (WIDESTR_WIDTH chars, WIDESTR_GROUPS distinct) + a value
    column, exercising variable-width -> device dictionary encoding in
    a skewed GROUP BY."""
    import hashlib

    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.block import Dictionary
    from trino_tpu.connectors.memory import create_memory_connector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.engine import LocalQueryRunner, Session

    vals = [
        hashlib.sha256(f"widestr-{i}".encode()).hexdigest()[:WIDESTR_WIDTH]
        .ljust(WIDESTR_WIDTH, "x")
        for i in range(WIDESTR_GROUPS)
    ]
    rng = np.random.default_rng(7)
    # zipf-ish skew: a few huge groups plus a long tail
    codes = (
        rng.zipf(1.3, WIDESTR_ROWS).astype(np.int64) % WIDESTR_GROUPS
    )
    v = rng.integers(0, 1_000_000, WIDESTR_ROWS, dtype=np.int64)
    mem = create_memory_connector()
    mem.load_table(
        "bench", "widestr",
        [ColumnMetadata("s", T.VARCHAR), ColumnMetadata("v", T.BIGINT)],
        [codes, v], None, [Dictionary(vals), None],
    )
    batch_rows = int(os.environ.get("BENCH_BATCH_ROWS", str(1 << 22)))
    r = LocalQueryRunner(
        Session(catalog="memory", schema="bench", batch_rows=batch_rows)
    )
    r.register_catalog("memory", mem)
    return r


def run_benches() -> dict:
    """All configs on this process's default jax platform. Returns
    {metric_name: seconds}. Runners are built per (sf, union-of-columns)
    so the two SF-large configs share one generation pass per table."""
    out = {}
    by_sf = {}
    for name, sf in _configs():
        if name not in TABLE_COLUMNS:
            continue  # q72/widestr build their own runners below
        by_sf.setdefault(sf, {})
        for table, cols in TABLE_COLUMNS[name].items():
            cur = by_sf[sf].setdefault(table, [])
            for c in cols:
                if c not in cur:
                    cur.append(c)
    runners = {}
    for sf, tables in by_sf.items():
        print(f"bench: generating sf={sf:g} tables...", file=sys.stderr, flush=True)
        runners[sf] = _make_runner(sf, tables)
    for name, sf in _configs():
        # SF-large configs trim one run, but never EXCEED the requested
        # count (the CPU baseline passes BENCH_RUNS=1 and means it)
        runs = RUNS if sf <= 1 else min(RUNS, max(2, RUNS - 1))
        print(f"bench: running {name} sf={sf:g}...", file=sys.stderr, flush=True)
        t0 = time.time()
        if name == "q72":
            runner = _make_tpcds_runner(sf)
        elif name == "widestr":
            runner = _make_widestr_runner()
        else:
            runner = runners[sf]
        out[f"{name}_sf{sf:g}"] = round(
            _median_wall(runner, SQL[name], runs), 4
        )
        print(
            f"bench: {name} sf={sf:g} wall={out[f'{name}_sf{sf:g}']}s "
            f"(total {time.time()-t0:.0f}s incl. prewarm)",
            file=sys.stderr, flush=True,
        )
    return out


PROBE_ROWS = 1_000_000

# env for the CPU-baseline subprocess: BENCH_PLATFORM is what actually
# demotes the child (sitecustomize pins JAX_PLATFORMS before we run);
# JAX_PLATFORMS rides along for the compile-cache opt-out in jaxcfg.
# Each platform runs its better batch size — the device default (4M)
# exists to amortize the tunneled link's per-dispatch RTT, which does
# not apply on CPU, where 1M batches are cache-friendlier (measured:
# SF1 CPU times got WORSE at 4M). Pinning also keeps the on-disk
# baseline cache consistent across device-side tuning changes.
_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_PLATFORM": "cpu",
    "BENCH_RUNS": "1",
    "BENCH_BATCH_ROWS": str(1 << 20),
}


def probe_gbs(n: int = PROBE_ROWS) -> float:
    """Hash-probe throughput in GB/s of probe-side key bytes (the
    BASELINE.json 'hash-probe GB/s per chip' metric). Measured with the
    marginal-device-time slope (benchmarks/devtime): the tunneled link
    moves data at ~25MB/s with ~130ms RTT, so any methodology that
    fetches the (lo, counts) outputs bills the LINK, not the chip —
    r3's number under-reported the kernel by ~3x this way."""
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.devtime import devtime as _measure
    from trino_tpu.ops import join as J

    rng = np.random.default_rng(0)
    build_n = n // 8
    bkeys = [jnp.asarray(np.arange(build_n, dtype=np.int64))]
    bvalids = [jnp.ones(build_n, dtype=jnp.bool_)]
    lookup = J.build_lookup(bkeys, bvalids, jnp.ones(build_n, dtype=jnp.bool_))
    pkeys = [jnp.asarray(rng.integers(0, build_n * 2, n).astype(np.int64))]
    pvalids = [jnp.ones(n, dtype=jnp.bool_)]
    plive = jnp.ones(n, dtype=jnp.bool_)

    def run():
        return J.probe_counts(lookup, pkeys, pvalids, plive)

    secs = _measure(run)
    return round(n * 8 / secs / 1e9, 2)


def _run_one_subprocess(name: str, sf: float, platform_env: dict,
                        timeout_s: int):
    """One config in an isolated subprocess (a first-compile that runs
    away must never wedge the whole bench — the driver runs this
    un-supervised at round end). Child stderr streams live to our
    stderr as it happens (buffering it until completion destroys the
    progress trail when a timeout kills the child). Returns
    (seconds, platform) or (None, None)."""
    env = dict(os.environ, BENCH_INNER="1", BENCH_ONLY=f"{name}:{sf:g}")
    env.update(platform_env)
    tag = "cpu" if platform_env.get("JAX_PLATFORMS") == "cpu" else "dev"
    out_lines: list = []
    err_tail: list = []

    def _pump_err(pipe):
        for line in pipe:
            line = line.rstrip("\n")
            err_tail.append(line)
            del err_tail[:-15]
            if line.startswith("bench:"):
                print(f"[{tag}] {line}", file=sys.stderr, flush=True)

    def _pump_out(pipe):
        for line in pipe:
            out_lines.append(line.rstrip("\n"))

    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except Exception as ex:
        print(f"bench: {name} sf={sf:g} [{tag}] skipped ({type(ex).__name__})",
              file=sys.stderr, flush=True)
        return None, None
    threads = [
        threading.Thread(target=_pump_err, args=(proc.stderr,), daemon=True),
        threading.Thread(target=_pump_out, args=(proc.stdout,), daemon=True),
    ]
    for t in threads:
        t.start()
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        print(f"bench: {name} sf={sf:g} [{tag}] skipped (timeout {timeout_s}s)",
              file=sys.stderr, flush=True)
        return None, None
    for t in threads:
        t.join(timeout=5)
    payload = [ln for ln in out_lines if ln.strip()]
    if not payload:
        # inner crash: surface the traceback tail, not an IndexError
        for line in err_tail:
            print(f"bench[inner/{tag}]: {line}", file=sys.stderr, flush=True)
        print(
            f"bench: {name} sf={sf:g} [{tag}] inner exited "
            f"rc={proc.returncode} with no result",
            file=sys.stderr, flush=True,
        )
        return None, None
    try:
        rec = json.loads(payload[-1])
        return rec[f"{name}_sf{sf:g}"], rec.get("_platform")
    except Exception as ex:
        print(f"bench: {name} sf={sf:g} [{tag}] unparseable result "
              f"({type(ex).__name__})", file=sys.stderr, flush=True)
        return None, None


_BENCH_DEV_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_DEV.json"
)


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _load_bench_dev() -> dict:
    try:
        with open(_BENCH_DEV_FILE) as f:
            return json.load(f)
    except Exception:
        return {"records": []}


def record_bench_dev(config: str, wall_s: float, platform: str,
                     note: str = "") -> None:
    """Append a real-chip measurement to the committed BENCH_DEV.json.

    r4's perf story evaporated when the driver-run bench hit a backend
    outage: every device number lived only in commit messages. This
    file is the machine-readable dev-loop record (config, wall, git
    SHA, platform) that survives in the repo snapshot regardless of
    whether the chip is reachable at round end (the benchto repeat-
    record discipline, testing/trino-benchto-benchmarks tpch.yaml)."""
    rec = {
        "config": config,
        "wall_s": round(wall_s, 4),
        "platform": platform,
        "git": _git_sha(),
        "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
    }
    if note:
        rec["note"] = note
    try:
        cur = _load_bench_dev()
        cur.setdefault("records", []).append(rec)
        # newest measurement per (config, platform, git) wins; cap
        # history so a re-run loop on one config cannot evict others
        seen = set()
        dedup = []
        for r in reversed(cur["records"]):
            key = (
                (r.get("config"), r.get("platform"), r.get("git"))
                if isinstance(r, dict) else None
            )
            if key is None or key in seen:
                continue
            seen.add(key)
            dedup.append(r)
        cur["records"] = list(reversed(dedup))[-200:]
        tmp = _BENCH_DEV_FILE + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cur, f, indent=1)
            f.write("\n")
        os.replace(tmp, _BENCH_DEV_FILE)
    except Exception:
        pass  # the record is best-effort; never fail a measurement


def latest_dev_walls() -> dict:
    """Newest recorded measurement per config from BENCH_DEV.json.
    Tolerates hand-edited/merge-damaged records (this path feeds the
    must-always-emit device_unavailable record)."""
    out: dict = {}
    for rec in _load_bench_dev().get("records", []):
        try:
            if rec.get("platform") == "cpu":
                continue
            entry = {
                "wall_s": rec["wall_s"], "git": rec.get("git"),
                "ts": rec.get("ts"),
            }
            if rec.get("note"):
                entry["note"] = rec["note"]
            out[rec["config"]] = entry
        except (TypeError, KeyError, AttributeError):
            continue
    return out


def _preflight_device(timeouts: Sequence[int] = (45, 75)) -> tuple:
    """Initialize the backend once in a child before committing to the
    full config matrix. r4's bench looped table-generation against a
    dead TPU backend for its whole budget (BENCH_r04.json rc=124);
    this bounds that failure mode to ~2 minutes: escalating-timeout
    child attempts (a healthy-but-slow init that misses the first
    window gets a longer second one), then the caller emits an explicit
    device_unavailable record. Returns (platform | None, tail)."""
    code = (
        "import jax, json, sys;"
        "d = jax.devices();"
        "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))"
    )
    tail: list = []
    for i, timeout_s in enumerate(timeouts):
        if i:
            print("bench: preflight retry in 5s...",
                  file=sys.stderr, flush=True)
            time.sleep(5)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            tail.append(f"attempt {i + 1}: init timeout after {timeout_s}s")
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            try:
                info = json.loads(proc.stdout.strip().splitlines()[-1])
                print(
                    f"bench: preflight ok — platform={info['platform']} "
                    f"n={info['n']} (attempt {i + 1})",
                    file=sys.stderr, flush=True,
                )
                return info["platform"], tail
            except Exception:
                pass
        err = [ln for ln in proc.stderr.splitlines() if ln.strip()][-4:]
        tail.append(f"attempt {i + 1}: rc={proc.returncode} " + " | ".join(err))
    return None, tail


_BASELINE_FILE = os.path.join(_TABLE_CACHE_DIR, "baselines.json")

# Cached CPU baselines are only comparable while the engine's CPU path
# and the baseline batch config stay fixed (VERDICT r3 weak #2: a stale
# cached baseline overstated Q3 SF10 by 1.6x after CPU batch tuning).
# Bump the epoch whenever engine changes could move CPU times.
_CPU_BASELINE_EPOCH = "r4-syncfree-join-agg"


def _baseline_cache_key(key: str) -> str:
    return f"{key}@{_CPU_BASELINE_EPOCH}@b{_CPU_ENV['BENCH_BATCH_ROWS']}"


def _load_cached_baselines() -> dict:
    try:
        with open(_BASELINE_FILE) as f:
            return json.load(f)
    except Exception:
        return {}


def _save_cached_baseline(key: str, secs: float) -> None:
    try:
        os.makedirs(_TABLE_CACHE_DIR, exist_ok=True)
        cur = _load_cached_baselines()
        cur[_baseline_cache_key(key)] = {
            "cpu_s": secs, "ts": time.strftime("%Y-%m-%d %H:%M"),
        }
        tmp = _BASELINE_FILE + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cur, f)
        os.replace(tmp, _BASELINE_FILE)
    except Exception:
        pass


def _emit(device: dict, baseline: dict, gbs, cached=None) -> None:
    """Print the driver's ONE JSON line reflecting everything measured
    so far (flushed). Called after every completed config: the LAST
    stdout line is the record, so each call supersedes the previous and
    a kill at any point still leaves a complete result behind.

    `cached` holds CPU baselines measured by a PREVIOUS bench run on
    this host (the SF10 CPU engine runs for many minutes and does not
    always fit the driver's budget); they fill gaps with explicit
    provenance (cpu_source) and fresh measurements always win."""
    extra = {}
    cached = cached or {}
    for k, v in device.items():
        extra[k] = {"wall_s": v}
        if k in baseline:
            extra[k]["cpu_s"] = baseline[k]
            extra[k]["vs_cpu"] = round(baseline[k] / v, 3)
        elif _baseline_cache_key(k) in cached:
            hit = cached[_baseline_cache_key(k)]
            extra[k]["cpu_s"] = hit["cpu_s"]
            extra[k]["vs_cpu"] = round(hit["cpu_s"] / v, 3)
            extra[k]["cpu_source"] = f"cached {hit['ts']}"
    if gbs is not None:
        extra["hash_probe"] = {"gb_s": gbs, "rows": PROBE_ROWS}

    if not device:
        # even total failure must emit the driver's one JSON line
        print(
            json.dumps(
                {"metric": "bench_failed", "value": 0.0, "unit": "s",
                 "vs_baseline": 0.0, "extra": {}}
            ),
            flush=True,
        )
        return
    # headline: the largest completed north-star config, preferring one
    # whose CPU baseline actually completed (a missing comparison must
    # not masquerade as a measured 1.0x)
    order = [f"q18_sf{SF_LARGE:g}", f"q3_sf{SF_LARGE:g}", "q3_sf1", "q1_sf1"]
    with_vs = [k for k in order if k in device and "vs_cpu" in extra[k]]
    candidates = with_vs or [k for k in order if k in device] or sorted(device)
    headline = candidates[0]
    value = device[headline]
    vs = extra[headline].get("vs_cpu", 1.0)
    if "vs_cpu" not in extra[headline]:
        extra["note"] = "cpu baseline missing for headline; vs_baseline unmeasured"
    elif headline in order:
        # demotion must be loud: a larger config completed on device but
        # lost its CPU baseline, so the headline metric name changed
        passed_over = [
            k for k in order[: order.index(headline)] if k in device
        ]
        if passed_over:
            extra["note"] = (
                f"headline demoted to {headline}; completed without cpu "
                f"baseline: {', '.join(passed_over)}"
            )
    print(
        json.dumps(
            {
                "metric": f"tpch_{headline}_wall",
                "value": value,
                "unit": "s",
                "vs_baseline": vs,
                "extra": extra,
            }
        ),
        flush=True,
    )


# chaos-smoke queries: the two plan shapes whose recovery paths differ
# most (scan->partial/final agg with an exchange in between, and a
# broadcast-join->agg with a build side worth losing mid-flight)
CHAOS_QUERIES = {
    "agg": (
        "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
        "from lineitem where l_shipdate <= date '1998-09-02' "
        "group by l_returnflag, l_linestatus "
        "order by l_returnflag, l_linestatus"
    ),
    "join": (
        "select n_name, count(*) c from supplier, nation "
        "where s_nationkey = n_nationkey group by n_name order by n_name"
    ),
}


def _chaos_smoke(argv) -> int:
    """--chaos-smoke [seed]: deterministic resiliency gate. Exit 0 iff
    every (query, fault class) run is answer-equal to the clean run and
    stays within its injected-failure bound; a failing run replays from
    the printed seed."""
    i = argv.index("--chaos-smoke")
    try:
        seed = int(argv[i + 1])
    except (IndexError, ValueError):
        seed = 42
    # the replica scenarios carve 2 sub-meshes from the device set —
    # make sure the host platform exposes enough devices before any
    # backend initializes (a real accelerator platform ignores this)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    from trino_tpu.runtime.chaos import (
        ADAPTIVE_CLASSES,
        FAULT_CLASSES,
        LIFECYCLE_CLASSES,
        RECOVERY_CLASSES,
        REPLICA_CLASSES,
        SERVING_CLASSES,
        TIMEBOUND_CLASSES,
        chaos_smoke,
    )

    print(f"bench: chaos smoke seed={seed} "
          f"fault_classes={','.join(FAULT_CLASSES)} "
          f"lifecycle={','.join(LIFECYCLE_CLASSES)} "
          f"timebound={','.join(TIMEBOUND_CLASSES)} "
          f"serving={','.join(SERVING_CLASSES)} "
          f"adaptive={','.join(ADAPTIVE_CLASSES)} "
          f"recovery={','.join(RECOVERY_CLASSES)},recovery_loaded_drain "
          f"replica={','.join(REPLICA_CLASSES)}")
    t0 = time.time()
    violations = chaos_smoke(seed, CHAOS_QUERIES)
    wall = time.time() - t0
    for v in violations:
        print(f"bench: chaos VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "chaos_smoke": {
            "seed": seed,
            "cases": len(CHAOS_QUERIES) * len(FAULT_CLASSES)
            + len(LIFECYCLE_CLASSES) + len(TIMEBOUND_CLASSES)
            + len(SERVING_CLASSES) + len(ADAPTIVE_CLASSES)
            + len(RECOVERY_CLASSES) + 1 + len(REPLICA_CLASSES),
            "violations": len(violations),
            "wall_s": round(wall, 2),
        }
    }))
    return 1 if violations else 0


# serve-smoke mix: the two analytic shapes the trace/chaos gates already
# exercise, plus point lookups — the statement class the plan cache,
# admission fast path, and micro-batcher were built for
SERVE_QUERIES = {"q1": Q1, "q6": Q6}


def _serve_flag(argv, name: str, default, cast=float):
    if name in argv:
        try:
            return cast(argv[argv.index(name) + 1])
        except (IndexError, ValueError):
            pass
    return default


def _serve_smoke(argv) -> int:
    """--serve-smoke [seed]: serving-tier gate. Drives the statement
    protocol open-loop with >=8 concurrent clients on a q1/q6/point mix
    and exits 0 iff every result is oracle-equal, nothing was shed,
    the plan-cache hit rate stays >=90%, zero XLA lowerings happen
    after warm-up, p99 <= 5x p50, and the batched phase coalesces
    while staying oracle-equal."""
    i = argv.index("--serve-smoke")
    try:
        seed = int(argv[i + 1])
    except (IndexError, ValueError):
        seed = 7
    from trino_tpu.serving.harness import serve_smoke

    n_clients = int(_serve_flag(argv, "--serve-clients", 8, int))
    duration_s = _serve_flag(argv, "--serve-duration", 6.0)
    print(f"bench: serve smoke seed={seed} clients={n_clients} "
          f"duration={duration_s:g}s mix=q1,q6,point")
    t0 = time.time()
    report, violations = serve_smoke(
        SERVE_QUERIES, n_clients=n_clients, duration_s=duration_s,
        seed=seed,
    )
    for v in violations:
        print(f"bench: serve VIOLATION: {v}", file=sys.stderr)
    report["violations"] = len(violations)
    report["wall_total_s"] = round(time.time() - t0, 2)
    print(json.dumps({"serve_smoke": report}))
    return 1 if violations else 0


def _serve(argv) -> int:
    """--serve: tunable open-loop load run (no gates, just the report).
    Knobs: --serve-clients N --serve-duration S --serve-rate QPS
    --serve-util U --serve-window MS --serve-seed N.
    --serve-replicas 1,2,4 switches to the replica sweep: the same
    mixed workload is offered at a FIXED rate (derived once, from the
    first arm) to a replicated mesh runner per arm, reporting QPS and
    p50/p99 per replica count — and gating that QPS does not degrade
    as replicas are added, no arm sheds, and tail bounds hold."""
    if _serve_flag(argv, "--serve-replicas", None, str) is not None:
        return _serve_replica_sweep(argv)
    from trino_tpu.serving.harness import run_serve_load

    report = run_serve_load(
        queries=SERVE_QUERIES,
        n_clients=int(_serve_flag(argv, "--serve-clients", 8, int)),
        duration_s=_serve_flag(argv, "--serve-duration", 6.0),
        rate_qps=_serve_flag(argv, "--serve-rate", None),
        utilization=_serve_flag(argv, "--serve-util", 0.5),
        micro_batch_window_ms=_serve_flag(argv, "--serve-window", 3.0),
        seed=int(_serve_flag(argv, "--serve-seed", 7, int)),
    )
    print(json.dumps({"serve": report}))
    return 0


def _serve_replica_sweep(argv) -> int:
    """--serve --serve-replicas 1,2,4: the PR 8 mixed workload against
    a replicated mesh serving plane, one arm per replica count. Each
    arm builds a distributed runner whose mesh is carved into R
    sub-meshes; every replica is warmed before the measured phase
    (warmup_rounds=R) and all arms share ONE offered rate, derived from
    the first arm's warm service times, so per-arm QPS and percentiles
    are comparable. Replicas are the mesh plane's units of serving
    concurrency (one program per sub-mesh at a time), so QPS must not
    DEGRADE as replicas are added while the offered load holds. Exit 1
    if any arm sheds, mismatches, errors, compiles after warmup, drops
    QPS below the 1-replica arm by more than 10%, or blows the tail
    bound (p99 <= 8x p50)."""
    if os.environ.get("SERVE_SWEEP_INNER") != "1":
        env = dict(os.environ)
        env["SERVE_SWEEP_INNER"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + argv[1:],
            env=env,
        ).returncode

    import jax

    jax.config.update("jax_platforms", "cpu")

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.metrics import install_xla_compile_listener
    from trino_tpu.serving.harness import run_serve_load

    install_xla_compile_listener()
    arms_spec = _serve_flag(argv, "--serve-replicas", "1,2,4", str)
    arm_replicas = [int(x) for x in arms_spec.split(",") if x.strip()]
    n_clients = int(_serve_flag(argv, "--serve-clients", 8, int))
    duration_s = _serve_flag(argv, "--serve-duration", 6.0)
    seed = int(_serve_flag(argv, "--serve-seed", 7, int))
    n_dev = len(jax.devices())
    print(f"bench: serve replica sweep arms={arm_replicas} "
          f"({n_dev}-device cpu mesh, clients={n_clients}, "
          f"duration={duration_s:g}s)")

    def mk(n_replicas: int):
        r = DistributedQueryRunner(
            Session(
                catalog="tpch", schema="tiny",
                mesh_replicas=n_replicas,
                mesh_chunk_rows=512,
                mesh_checkpoint_interval_chunks=4,
            ),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        return r

    violations = []
    arms = {}
    rate = _serve_flag(argv, "--serve-rate", None)
    for n_replicas in arm_replicas:
        runner = mk(n_replicas)
        report = run_serve_load(
            queries=SERVE_QUERIES,
            n_clients=n_clients,
            duration_s=duration_s,
            rate_qps=rate,
            utilization=_serve_flag(argv, "--serve-util", 0.9),
            # batched burst runs on the replicated runner too: the
            # combined IN-list lookups must ride the MeshScheduler
            # fast lane on the replica run queues (gated below)
            batch_phase_s=_serve_flag(argv, "--serve-batch", 1.0),
            seed=seed,
            runner=runner,
            warmup_rounds=max(1, n_replicas),
        )
        # all arms offer the SAME load: reuse the first arm's derived
        # rate so the sweep compares service capacity, not schedules
        rate = report["rate_qps"]
        rm = getattr(runner, "_replicas", None)
        arms[n_replicas] = {
            k: report[k]
            for k in ("rate_qps", "offered", "completed", "qps",
                      "p50_ms", "p95_ms", "p99_ms", "p99_over_p50",
                      "shed", "mismatches", "error_count",
                      "plan_cache_hit_rate", "xla_compiles_after_warmup")
        }
        arms[n_replicas]["replica_stats"] = rm.stats() if rm else None
        bp = report.get("batch_phase")
        if bp is not None:
            arms[n_replicas]["batch_phase"] = {
                k: bp[k]
                for k in ("queries", "mismatches", "error_count",
                          "batches", "batched_queries", "mesh_fast_lane")
            }
            if bp["mismatches"] or bp["error_count"]:
                violations.append(
                    f"arm r={n_replicas}: batch phase "
                    f"{bp['mismatches']} mismatches, "
                    f"{bp['error_count']} errors"
                )
            if bp["batches"] == 0 or bp["batched_queries"] <= bp["batches"]:
                violations.append(
                    f"arm r={n_replicas}: batch phase never coalesced "
                    f"(batches={bp['batches']}, "
                    f"batched_queries={bp['batched_queries']})"
                )
            if bp["mesh_fast_lane"] < bp["batches"]:
                violations.append(
                    f"arm r={n_replicas}: batched lookups bypassed the "
                    f"mesh scheduler fast lane "
                    f"(fast submissions {bp['mesh_fast_lane']} < "
                    f"batches {bp['batches']})"
                )
        if report["mismatches"]:
            violations.append(
                f"arm r={n_replicas}: {report['mismatches']} results "
                "diverged from the oracle"
            )
        if report["error_count"]:
            violations.append(
                f"arm r={n_replicas}: {report['error_count']} errors "
                f"(first: {report['errors'][:1]})"
            )
        if report["shed"]:
            violations.append(
                f"arm r={n_replicas}: {report['shed']} sheds under the "
                "shared offered rate"
            )
        if report["xla_compiles_after_warmup"]:
            violations.append(
                f"arm r={n_replicas}: "
                f"{report['xla_compiles_after_warmup']} XLA lowerings "
                "in the measured phase (warmup_rounds missed a replica)"
            )
        if report["p99_over_p50"] > 8.0:
            violations.append(
                f"arm r={n_replicas}: p99/p50 = "
                f"{report['p99_over_p50']} blows the 8x tail bound"
            )
    base_qps = arms[arm_replicas[0]]["qps"]
    for n_replicas in arm_replicas[1:]:
        if arms[n_replicas]["qps"] < 0.90 * base_qps:
            violations.append(
                f"arm r={n_replicas}: qps {arms[n_replicas]['qps']} "
                f"degraded >10% below the 1-replica arm ({base_qps})"
            )
    for v in violations:
        print(f"bench: serve-sweep VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "serve_replica_sweep": {
            "devices": n_dev,
            "arms": {str(k): v for k, v in arms.items()},
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _parse_compile_lines(text: str) -> dict:
    """Pull the compile-regime counters out of an EXPLAIN ANALYZE plan
    text (census + warmup + cache lines, engine._explain_analyze)."""
    import re

    out: dict = {}
    for key, pat in (
        ("expected_lowerings", r"expected_xla_lowerings=(\d+)"),
        ("observed_classes", r"observed_shape_classes=(\d+)"),
        ("xla_compiles", r"xla_compiles_this_query=(\d+)"),
    ):
        m = re.search(pat, text)
        if m:
            out[key] = int(m.group(1))
    m = re.search(
        r"warmup: mode=(\w+) entries=(\d+) compiled=(\d+) failed=(\d+) "
        r"skipped=(\d+)(?: hits=(\d+) misses=(\d+))?",
        text,
    )
    if m:
        out["warmup"] = {
            "mode": m.group(1),
            "entries": int(m.group(2)),
            "compiled": int(m.group(3)),
            "failed": int(m.group(4)),
            "skipped": int(m.group(5)),
        }
        if m.group(6) is not None:
            out["warmup"]["hits"] = int(m.group(6))
            out["warmup"]["misses"] = int(m.group(7))
    return out


def _warmup_smoke(argv) -> int:
    """--warmup-smoke: compile-regime gate. Runs the q72-class plan
    (deep multi-build join tree) twice from a cold compile state on the
    CPU backend — once with warmup off, once with warmup_mode=block —
    and prints one JSON line with per-arm compile counts and walls.
    Exit 1 iff the warmup-on arm observes more distinct shape classes
    at runtime than the census predicted (shape stabilization failed to
    land execution on the predicted lowerings) or the arms disagree on
    the answer."""
    import jax

    from trino_tpu.compile.cache import PROGRAM_CACHE
    from trino_tpu.compile.warmup import reset_warm_classes
    from trino_tpu.connectors.tpcds import create_tpcds_connector
    from trino_tpu.engine import LocalQueryRunner, Session

    def run_arm(warmup_mode: str) -> dict:
        # cold start: drop the engine's program cache, jax's dispatch
        # caches, and the warm-class registry so each arm pays (or
        # warms) its own compiles
        PROGRAM_CACHE.clear()
        reset_warm_classes()
        jax.clear_caches()
        r = LocalQueryRunner(Session(catalog="tpcds", schema="tiny"))
        r.register_catalog("tpcds", create_tpcds_connector())
        r.session.set_property("warmup_mode", warmup_mode)
        t0 = time.time()
        text = r.execute("EXPLAIN ANALYZE " + Q72).only_value()
        wall = time.time() - t0
        rows = r.execute(Q72).rows
        stats = _parse_compile_lines(text)
        stats["warmup_mode"] = warmup_mode
        stats["wall_s"] = round(wall, 2)
        return stats, rows

    print("bench: warmup smoke (q72-class plan, tpcds tiny, CPU ok)")
    base, base_rows = run_arm("off")
    warm, warm_rows = run_arm("block")
    violations = []
    expected = warm.get("expected_lowerings")
    observed = warm.get("observed_classes")
    if expected is None or observed is None:
        violations.append("compile census lines missing from EXPLAIN ANALYZE")
    elif observed > expected:
        violations.append(
            f"warmup-on run observed {observed} distinct shape classes, "
            f"census predicted {expected} — stabilization failed to land "
            "execution on the predicted lowerings"
        )
    if base_rows != warm_rows:
        violations.append("warmup changed the query answer")
    for v in violations:
        print(f"bench: warmup VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "warmup_smoke": {
            "query": "q72",
            "no_warmup": base,
            "with_warmup": warm,
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _trace_smoke(argv) -> int:
    """--trace-smoke: observability gate (runtime/tracing.py). Runs a
    traced distributed TPC-H query plus one chaos scenario, validates
    the exported span tree (invariants + Chrome trace-event schema),
    and measures tracing overhead traced-on vs traced-off on the Q1/Q6
    CPU pair. Exit 1 iff the trace fails to parse, an invariant is
    violated, a chaos annotation is missing, or overhead exceeds 5%
    wall on either query."""
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime import DistributedQueryRunner, Worker
    from trino_tpu.runtime.failure import FailureInjector
    from trino_tpu.runtime.tracing import check_span_invariants

    def cluster(tag, **session_kw):
        inj = FailureInjector()
        cats = CatalogManager()
        cats.register("tpch", create_tpch_connector())
        workers = [
            Worker(f"{tag}-w{i}", cats, failure_injector=inj)
            for i in range(2)
        ]
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny", **session_kw),
            worker_handles=workers, hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        return inj, r

    violations = []
    print("bench: trace smoke (distributed TPC-H, tpch tiny, CPU ok)")

    # 1. traced run: the exported tree is complete, valid, and renders
    # as loadable Chrome trace-event JSON
    _, traced = cluster("ts", query_trace="on")
    if not traced.execute(CHAOS_QUERIES["agg"]).rows:
        violations.append("traced query returned no rows")
    export = traced.query_trace_export(traced.last_query_id)
    if export is None:
        violations.append("traced query exported no trace")
        export = {"spans": []}
    violations += check_span_invariants(export)
    kinds = {s["kind"] for s in export["spans"]}
    missing = {"query", "phase", "stage", "task", "operator"} - kinds
    if missing:
        violations.append(f"trace missing span kinds: {sorted(missing)}")
    chrome = traced.query_chrome_trace(traced.last_query_id) or {}
    events = json.loads(json.dumps(chrome)).get("traceEvents", [])
    if not any(e.get("ph") == "X" for e in events):
        violations.append("chrome trace has no complete ('X') events")

    # 2. chaos scenario: a crash-injected FTE run still exports one
    # valid timeline, annotated where the fault and the retry landed
    inj, fte = cluster("tc", query_trace="on", retry_policy="task")
    inj.inject(where="start", kind="crash", fragment_id=0, partition=0,
               attempts=(0,), max_hits=1)
    try:
        if not fte.execute(CHAOS_QUERIES["join"]).rows:
            violations.append("chaos-injected query returned no rows")
    finally:
        inj.clear()
    chaos_export = fte.query_trace_export(fte.last_query_id)
    if chaos_export is None:
        violations.append("chaos-injected query exported no trace")
        chaos_export = {"spans": []}
    violations += check_span_invariants(chaos_export)
    task_events = [
        e["name"] for s in chaos_export["spans"] if s["kind"] == "task"
        for e in s["events"]
    ]
    stage_events = [
        e["name"] for s in chaos_export["spans"] if s["kind"] == "stage"
        for e in s["events"]
    ]
    if "chaos_fault" not in task_events:
        violations.append("chaos_fault annotation missing from task spans")
    if "task_retry" not in stage_events:
        violations.append("task_retry annotation missing from stage spans")

    # 3. overhead: best-of-N warm walls, traced-on vs traced-off, on
    # the Q1/Q6 pair (aggregation-heavy and scan-heavy) — the traced
    # arm pays operator spans + row counting, the baseline arm runs
    # with instrumentation gated off
    _, r_off = cluster("to")
    _, r_on = cluster("tn", query_trace="on")
    reps = 7
    overhead = {}
    for name, sql in (("q1", Q1), ("q6", Q6)):
        for r in (r_off, r_on):
            r.execute(sql)  # warm compiles before timing
        # interleave the arms so machine drift (page cache, turbo,
        # background load) lands on both equally; best-of-N per arm
        walls = {"off": float("inf"), "on": float("inf")}
        for _ in range(reps):
            for arm, r in (("off", r_off), ("on", r_on)):
                t0 = time.time()
                r.execute(sql)
                walls[arm] = min(walls[arm], time.time() - t0)
        pct = (walls["on"] - walls["off"]) / walls["off"] * 100.0
        overhead[name] = {
            "wall_off_s": round(walls["off"], 4),
            "wall_on_s": round(walls["on"], 4),
            "overhead_pct": round(pct, 2),
        }
        if pct > 5.0:
            violations.append(
                f"tracing overhead on {name}: {pct:.1f}% > 5% "
                f"(off={walls['off'] * 1000:.1f}ms "
                f"on={walls['on'] * 1000:.1f}ms)"
            )

    for v in violations:
        print(f"bench: trace VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "trace_smoke": {
            "spans": len(export["spans"]),
            "chaos_spans": len(chaos_export["spans"]),
            "overhead": overhead,
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _mesh_smoke(argv) -> int:
    """--mesh-smoke: CI gate for the chunked GSPMD mesh plane
    (parallel/mesh_chunk.py). Re-execs itself with an 8-virtual-device
    CPU host platform, then runs Q1, Q6 and a hash join chunked over
    the mesh and checks: answer-equality vs the page plane, at least
    one all_to_all exchange, zero new XLA lowerings when a query
    executes a second time, and a mid-query deadline kill that preempts
    between chunks with the typed EXCEEDED_TIME_LIMIT error and no
    page-plane fallback. Exit 1 on any violation."""
    if os.environ.get("MESH_SMOKE_INNER") != "1":
        # the 8-device mesh needs XLA_FLAGS before the backend
        # initializes, and the injected sitecustomize may have imported
        # jax already — a child process is the only clean slate
        env = dict(os.environ)
        env["MESH_SMOKE_INNER"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-smoke"],
            env=env,
        ).returncode

    import jax

    # legal until a backend initializes (see the BENCH_INNER note):
    # the mesh smoke is a CPU-semantics gate, not a device bench
    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel.mesh_chunk import LAST_RUN_INFO
    from trino_tpu.parallel.mesh_plan import MESH_COUNTERS
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.metrics import METRICS
    from trino_tpu.runtime.query_tracker import (
        EXCEEDED_TIME_LIMIT,
        QueryDeadlineError,
    )

    def mk(**session_kw):
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny", **session_kw),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        return r

    join = (
        "select o_orderpriority, count(*) c from orders join customer "
        "on o_custkey = c_custkey group by o_orderpriority "
        "order by o_orderpriority"
    )
    violations = []
    print(f"bench: mesh smoke ({n_dev}-device cpu mesh, tpch tiny)")
    if n_dev < 8:
        violations.append(f"expected an 8-device mesh, got {n_dev}")

    page = mk(mesh_execution=False)
    mesh = mk(mesh_chunk_rows=512)
    report = {}
    for name, sql in (("q1", Q1), ("q6", Q6), ("join", join)):
        before = dict(MESH_COUNTERS)
        expect = page.execute(sql).rows
        got = mesh.execute(sql).rows
        if mesh._last_data_plane != "mesh":
            violations.append(
                f"{name}: ran on {mesh._last_data_plane}, not the mesh "
                f"(fallback: {mesh.last_mesh_fallback})"
            )
        if got != expect:
            violations.append(f"{name}: mesh answer != page answer")
        a2a = MESH_COUNTERS["all_to_all"] - before["all_to_all"]
        # second execution of the same program: the chunk-step records
        # are cached, so NO new XLA lowerings may appear
        compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
        got2 = mesh.execute(sql).rows
        compiles = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
        if got2 != expect:
            violations.append(f"{name}: second mesh run diverged")
        if compiles > 0:
            violations.append(
                f"{name}: second execution lowered {compiles:g} new "
                "XLA programs (expected 0)"
            )
        report[name] = {
            "rows": len(got),
            "all_to_all": a2a,
            "chunks": LAST_RUN_INFO.get("chunks"),
            "relowerings_second_run": compiles,
        }
    if all(r["all_to_all"] <= 0 for r in report.values()):
        violations.append("no query exchanged via all_to_all")

    # mid-query deadline kill: warm the chunked programs, slow the
    # tracker tick so the chunk-boundary check is the enforcement path,
    # then run under a wall budget that expires inside the chunk loop
    killer = mk(mesh_chunk_rows=128)
    killer.execute(Q1)
    killer.query_tracker.tick_interval_s = 60.0
    killer.session.query_max_execution_time_s = 0.05
    kill_msg = None
    try:
        killer.execute(Q1)
        violations.append("deadline query completed instead of dying")
    except QueryDeadlineError as e:
        kill_msg = str(e)
        if EXCEEDED_TIME_LIMIT not in kill_msg:
            violations.append(f"kill not typed: {kill_msg}")
        if "mesh chunk" not in kill_msg:
            violations.append(
                f"kill did not preempt at a chunk boundary: {kill_msg}"
            )
    except Exception as e:
        violations.append(f"wrong kill type {type(e).__name__}: {e}")
    if killer.last_mesh_fallback is not None:
        violations.append(
            f"deadline kill fell back to the page plane: "
            f"{killer.last_mesh_fallback}"
        )
    report["deadline_kill"] = kill_msg

    for v in violations:
        print(f"bench: mesh VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "mesh_smoke": {
            "devices": n_dev,
            "queries": report,
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _resident_smoke(argv) -> int:
    """--resident-smoke: CI gate for the resident state tier
    (trino_tpu/resident/). Checks: (1) warm pinned point lookups beat
    the cold execute path on p50 with resident.hits > 0 and zero
    rebuild pins in the warm loop; (2) repeated pinned probes — and
    repeated post-compaction probes — mint zero new XLA lowerings;
    (3) answers stay oracle-equal through DML invalidation (generation
    bump -> rebuild), the delta-append path, and background compaction;
    (4) a zero pin budget degrades to the cold path without failing any
    lookup. Exit 1 on any violation."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from trino_tpu import types as Ty
    from trino_tpu.connectors.memory import create_memory_connector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.engine import LocalQueryRunner, Session
    from trino_tpu.resident import RESIDENT
    from trino_tpu.resident.fastlane import (
        drain_compactions,
        try_resident_lookup,
    )
    from trino_tpu.runtime.metrics import METRICS

    violations = []
    print("bench: resident smoke (memory connector, pinned fast lane)")
    mem = create_memory_connector()
    r = LocalQueryRunner(Session(
        catalog="memory", schema="s",
        resident_tables="s.kv", resident_delta_max_rows=64,
    ))
    r.register_catalog("memory", mem)
    n = 1000
    rng = np.random.default_rng(3)
    mem.load_table(
        "s", "kv",
        [ColumnMetadata("k", Ty.BIGINT), ColumnMetadata("v", Ty.BIGINT)],
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 1 << 30, n).astype(np.int64)],
    )
    RESIDENT.evict_all()
    RESIDENT.reset_stats()

    def oracle(k):
        return r.execute(f"select v from kv where k = {k}").rows

    def fast(k):
        res = try_resident_lookup(r, f"select v from kv where k = {k}")
        return None if res is None else res.rows

    # -- 1. build, then warm-loop latency + zero lowerings ------------
    if fast(7) != oracle(7):
        violations.append("first (build) lookup diverged from oracle")
    keys = [int(k) for k in rng.integers(0, n, 200)]
    fast(keys[0])  # one warm probe before timing
    pins0 = RESIDENT.stats()["pins"]
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    warm_times = []
    for k in keys:
        t0 = time.perf_counter()
        rows = fast(k)
        warm_times.append(time.perf_counter() - t0)
        if rows is None:
            violations.append(f"warm lookup k={k} fell to the cold path")
            break
    warm_compiles = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    if warm_compiles > 0:
        violations.append(
            f"warm probes lowered {warm_compiles:g} new XLA programs "
            "(expected 0)"
        )
    if RESIDENT.stats()["pins"] != pins0:
        violations.append("warm loop rebuilt the pinned table")
    if RESIDENT.stats()["hits"] <= 0:
        violations.append("no resident hits recorded")
    for k in keys[:5]:
        if fast(k) != oracle(k):
            violations.append(f"warm lookup k={k} diverged from oracle")
    cold_times = []
    for k in keys[:20]:
        t0 = time.perf_counter()
        oracle(k)
        cold_times.append(time.perf_counter() - t0)
    warm_p50 = sorted(warm_times)[len(warm_times) // 2]
    cold_p50 = sorted(cold_times)[len(cold_times) // 2]
    if warm_p50 >= cold_p50:
        violations.append(
            f"warm p50 {warm_p50 * 1e3:.3f}ms not below cold p50 "
            f"{cold_p50 * 1e3:.3f}ms"
        )

    # -- 2. DML invalidation: generation bump -> rebuild, oracle-equal
    r.execute("update kv set v = -1 where k = 7")
    if fast(7) != oracle(7) or fast(7) != [[-1]]:
        violations.append("post-UPDATE lookup not oracle-equal")
    if RESIDENT.stats()["evictions"] <= 0:
        violations.append("UPDATE did not evict the stale pin")

    # -- 3. delta-append path + background compaction -----------------
    pins_before_delta = RESIDENT.stats()["pins"]
    for i in range(40):  # delta_max_rows=64 -> compaction at 32
        r.execute(f"insert into kv values ({2000 + i}, {i})")
    drain_compactions()
    if RESIDENT.stats()["pins"] != pins_before_delta:
        violations.append(
            "delta appends re-pinned instead of re-keying the live pin"
        )
    if RESIDENT.stats()["compactions"] <= 0:
        violations.append("delta never crossed into background compaction")
    for k in (2000, 2039, 7, 500):
        if fast(k) != oracle(k):
            violations.append(
                f"post-delta/compaction lookup k={k} diverged from oracle"
            )
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    for k in keys[:50]:
        fast(k)
    post_compiles = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    if post_compiles > 0:
        violations.append(
            f"post-compaction probes lowered {post_compiles:g} new XLA "
            "programs (expected 0)"
        )

    # -- 4. pin-budget overflow degrades to the cold path -------------
    r.session.resident_pin_budget_mb = 0
    RESIDENT.evict_all()
    got = fast(7)
    if got != oracle(7):
        violations.append(
            f"zero-budget lookup failed or diverged (got {got})"
        )
    if RESIDENT.stats()["entries"] != 0:
        violations.append("zero-budget lookup left a pin behind")

    for v in violations:
        print(f"bench: resident VIOLATION: {v}", file=sys.stderr)
    stats = RESIDENT.stats()
    print(json.dumps({
        "resident_smoke": {
            "warm_p50_ms": round(warm_p50 * 1e3, 4),
            "cold_p50_ms": round(cold_p50 * 1e3, 4),
            "speedup": round(cold_p50 / max(warm_p50, 1e-9), 1),
            "hits": stats["hits"],
            "misses": stats["misses"],
            "pins": stats["pins"],
            "evictions": stats["evictions"],
            "compactions": stats["compactions"],
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _adaptive_smoke(argv) -> int:
    """--adaptive-smoke: CI gate for the adaptive execution tier
    (trino_tpu/adaptive/). A q72-class multi-join over the memory
    connector whose dimension stats LIE (a fan-out build side reported
    at 1/20th of its true cardinality), so the optimizer's first plan
    is wrong on purpose. Two arms run the same query over the same
    lying catalog: non-adaptive rides the bad plan; adaptive observes
    the completed build at the barrier, crosses the re-plan threshold,
    and re-optimizes the remainder seeded with observed stats. Exit 1
    iff the adaptive arm fails to re-plan, the arms disagree on the
    answer, the adaptive warm wall does not beat the non-adaptive warm
    wall, or the adaptive warm loop mints a new XLA lowering."""
    import dataclasses

    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.adaptive import SPOOL
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.engine import LocalQueryRunner, Session
    from trino_tpu.runtime.metrics import METRICS

    def build_catalog() -> MemoryConnector:
        conn = MemoryConnector()
        rng = np.random.default_rng(17)
        n, keys, fan = 50_000, 40, 20
        conn.load_table(
            "s", "facts",
            [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("k2", T.BIGINT),
             ColumnMetadata("v", T.BIGINT)],
            [rng.integers(0, keys, n).astype(np.int64),
             rng.integers(0, 1000, n).astype(np.int64),
             rng.integers(0, 100, n).astype(np.int64)],
        )
        # d1 fans out (each key 20x); the lie below hides the fan-out
        conn.load_table(
            "s", "d1",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("tag", T.BIGINT)],
            [np.repeat(np.arange(keys, dtype=np.int64), fan),
             np.arange(keys * fan, dtype=np.int64)],
        )
        conn.load_table(
            "s", "d2",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
            [np.arange(2, dtype=np.int64), np.arange(2, dtype=np.int64)],
        )
        real = conn.metadata.get_table_statistics

        def lying(handle):
            ts = real(handle)
            if handle.table == "d1" and ts.row_count is not None:
                return dataclasses.replace(
                    ts, row_count=ts.row_count / 20.0, columns={}
                )
            return ts

        conn.metadata.get_table_statistics = lying
        return conn

    sql = (
        "select count(*), sum(f.v + d1.tag + d2.w) from facts f "
        "join d1 on f.k1 = d1.k join d2 on f.k2 = d2.k"
    )

    def run_arm(adaptive: bool) -> dict:
        SPOOL.clear()
        r = LocalQueryRunner(Session(
            catalog="memory", schema="s",
            adaptive_execution=adaptive,
            adaptive_replan_threshold=2.0,
        ))
        r.register_catalog("memory", build_catalog())
        t0 = time.time()
        rows = r.execute(sql).rows
        cold = time.time() - t0
        walls = []
        compiles0 = METRICS.counter("xla_compiles")
        for _ in range(3):
            t0 = time.time()
            assert r.execute(sql).rows == rows
            walls.append(time.time() - t0)
        new_lowerings = METRICS.counter("xla_compiles") - compiles0
        report = r._last_adaptive_report
        return {
            "rows": rows,
            "cold_wall_s": round(cold, 3),
            "warm_wall_s": round(sorted(walls)[1], 4),  # median of 3
            "warm_new_lowerings": int(new_lowerings),
            "replans": report.replans if report is not None else 0,
            "observations": (
                len(report.observations) if report is not None else 0
            ),
        }

    print("bench: adaptive smoke (misestimated q72-class join, "
          "memory connector, CPU ok)")
    base = run_arm(adaptive=False)
    adapt = run_arm(adaptive=True)
    violations = []
    if adapt["replans"] < 1:
        violations.append(
            "adaptive arm never re-planned — the misestimate was not "
            "observed at the barrier"
        )
    if base["rows"] != adapt["rows"]:
        violations.append(
            f"arms disagree: base={base['rows']} adaptive={adapt['rows']}"
        )
    if adapt["warm_wall_s"] >= base["warm_wall_s"]:
        violations.append(
            f"adaptive warm wall {adapt['warm_wall_s']}s did not beat "
            f"non-adaptive {base['warm_wall_s']}s"
        )
    if adapt["warm_new_lowerings"] != 0:
        violations.append(
            f"adaptive warm loop minted {adapt['warm_new_lowerings']} "
            "new XLA lowerings — re-planned programs left the "
            "capacity ladder"
        )
    for v in violations:
        print(f"bench: adaptive VIOLATION: {v}", file=sys.stderr)
    base.pop("rows")
    adapt.pop("rows")
    print(json.dumps({
        "adaptive_smoke": {
            "query": "q72-class misestimated join",
            "base": base,
            "adaptive": adapt,
            "speedup": round(
                base["warm_wall_s"] / max(adapt["warm_wall_s"], 1e-9), 2
            ),
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


# recovery-smoke query: a q72-class deep multi-build join (4 tables,
# grouped agg) that the mesh plane chunks into dozens of steps — deep
# enough that discarding completed chunks is genuinely expensive
RECOVERY_Q = (
    "select c_mktsegment, n_name, count(*) c, sum(l_quantity) q "
    "from lineitem join orders on l_orderkey = o_orderkey "
    "join customer on o_custkey = c_custkey "
    "join nation on c_nationkey = n_nationkey "
    "group by c_mktsegment, n_name order by c_mktsegment, n_name"
)


def _recovery_smoke(argv) -> int:
    """--recovery-smoke: CI gate for the recovery tier
    (trino_tpu/recovery/). An injected device loss lands at chunk k of
    K on a q72-class join, twice: the RESTART arm runs with
    checkpointing off — the fault discards every completed chunk and
    the page plane recomputes from zero (the pre-recovery behavior) —
    and the RESUME arm runs with chunk checkpointing on, so the mesh
    resumes from its last checkpoint. Gates: both arms oracle-equal to
    the page plane, the resume arm stays ON the mesh, resumes >= 1,
    re-executes fewer chunks than the restart discards, beats the
    restart wall, and mints zero new XLA lowerings (resumed carries
    land on already-warm capacity-ladder rungs). Exit 1 on violation."""
    if os.environ.get("RECOVERY_SMOKE_INNER") != "1":
        # same clean-slate re-exec as --mesh-smoke: the multi-device
        # host platform must be configured before jax initializes
        env = dict(os.environ)
        env["RECOVERY_SMOKE_INNER"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--recovery-smoke"],
            env=env,
        ).returncode

    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.parallel.mesh_chunk import LAST_RUN_INFO, MeshDeviceLost
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.metrics import METRICS

    def mk(**session_kw):
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny", **session_kw),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        return r

    violations = []
    print(f"bench: recovery smoke ({n_dev}-device cpu mesh, "
          "q72-class join, tpch tiny)")
    page = mk(mesh_execution=False)
    oracle = page.execute(RECOVERY_Q).rows

    resume = mk(mesh_chunk_rows=256, mesh_checkpoint_interval_chunks=4)
    warm = resume.execute(RECOVERY_Q).rows  # warm clean run
    if resume._last_data_plane != "mesh":
        violations.append(
            f"clean run took {resume._last_data_plane}, not the mesh "
            f"(fallback: {resume.last_mesh_fallback})"
        )
    if warm != oracle:
        violations.append("clean mesh run != page-plane oracle")
    K = int(LAST_RUN_INFO.get("chunks") or 0)
    fault_k = max(1, (3 * K) // 4)

    def make_hook():
        state = {"fired": 0}

        def hook(k, Ktot):
            if k == fault_k and not state["fired"]:
                state["fired"] = 1
                raise MeshDeviceLost(
                    f"recovery smoke: injected device loss at chunk "
                    f"{k}/{Ktot}"
                )

        return hook, state

    # RESTART arm: no checkpoints — the fault unwinds the whole mesh
    # run and the page plane recomputes from zero
    restart = mk(mesh_chunk_rows=256)
    restart.execute(RECOVERY_Q)  # warm its mesh programs too
    hook, st_restart = make_hook()
    mesh_chunk.MESH_FAULT_HOOK = hook
    t0 = time.time()
    try:
        rows_restart = restart.execute(RECOVERY_Q).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    wall_restart = time.time() - t0
    if rows_restart != oracle:
        violations.append("restart arm diverged from the oracle")
    if not st_restart["fired"]:
        violations.append("restart arm: fault never fired")

    # RESUME arm: same fault, checkpoint every 4 chunks
    hook, st_resume = make_hook()
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    mesh_chunk.MESH_FAULT_HOOK = hook
    t0 = time.time()
    try:
        rows_resume = resume.execute(RECOVERY_Q).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    wall_resume = time.time() - t0
    new_lowerings = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    info = dict(LAST_RUN_INFO)
    re_executed = int(info.get("executed_chunk_steps") or 0) - K
    if rows_resume != oracle:
        violations.append("resume arm diverged from the oracle")
    if not st_resume["fired"]:
        violations.append("resume arm: fault never fired")
    elif resume._last_data_plane != "mesh":
        violations.append(
            f"resume arm left the mesh plane "
            f"({resume._last_data_plane}: {resume.last_mesh_fallback})"
        )
    elif not info.get("resumes"):
        violations.append(f"resume arm never resumed ({info})")
    elif re_executed >= fault_k:
        violations.append(
            f"resume arm re-executed {re_executed} chunks — the "
            f"restart arm discards {fault_k}; the checkpoint saved "
            "nothing"
        )
    if wall_resume >= wall_restart:
        violations.append(
            f"resume wall {wall_resume:.2f}s did not beat the "
            f"full-restart wall {wall_restart:.2f}s"
        )
    if new_lowerings > 0:
        violations.append(
            f"resumed run lowered {new_lowerings:g} new XLA programs "
            "(expected 0: carries are ladder-stable)"
        )

    for v in violations:
        print(f"bench: recovery VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "recovery_smoke": {
            "devices": n_dev,
            "chunks": K,
            "fault_chunk": fault_k,
            "resumed_from_chunk": info.get("resumed_from_chunk"),
            "re_executed_chunks": re_executed,
            "restart_wall_s": round(wall_restart, 3),
            "resume_wall_s": round(wall_resume, 3),
            "new_lowerings_on_resume": new_lowerings,
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _failover_smoke(argv) -> int:
    """--failover-smoke: CI gate for the replicated serving plane
    (trino_tpu/runtime/replicas.py). Two replicas are carved from an
    8-device CPU mesh; an injected device loss hard-kills whichever
    replica serves the query at chunk 3K/4, twice: the RESTART arm runs
    with checkpointing off — the sibling sub-mesh takes the query over
    but must recompute from chunk 0 — and the RESUME arm runs with
    chunk checkpointing on, so the sibling restores the host-portable
    checkpoint and continues from chunk k. Gates: both arms
    oracle-equal and ON the mesh plane (failover, not page fallback),
    exactly one failover each, the resume arm re-executes fewer chunks
    than the restart arm recomputes, beats its wall, mints zero new XLA
    lowerings (the sibling is warm), and a deadline expiring during the
    failed-over stretch still kills typed, naming the resume point and
    replica. Exit 1 on violation."""
    if os.environ.get("FAILOVER_SMOKE_INNER") != "1":
        # same clean-slate re-exec as --recovery-smoke: the multi-device
        # host platform must be configured before jax initializes
        env = dict(os.environ)
        env["FAILOVER_SMOKE_INNER"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--failover-smoke"],
            env=env,
        ).returncode

    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.parallel.mesh_chunk import LAST_RUN_INFO, MeshDeviceLost
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.metrics import METRICS
    from trino_tpu.runtime.query_tracker import ExceededTimeLimitError

    def mk(**session_kw):
        r = DistributedQueryRunner(
            Session(
                catalog="tpch", schema="tiny", mesh_replicas=2,
                mesh_chunk_rows=256, mesh_resume_attempts=0,
                **session_kw,
            ),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        return r

    violations = []
    print(f"bench: failover smoke ({n_dev}-device cpu mesh, 2 replicas, "
          "q72-class join, tpch tiny)")
    page = mk(mesh_execution=False)
    oracle = page.execute(RECOVERY_Q).rows

    def warm(runner) -> int:
        """Warm BOTH replicas (sequential placements round-robin) and
        learn K; returns the chunk count of the warm run."""
        for _ in range(2):
            rows = runner.execute(RECOVERY_Q).rows
            if rows != oracle:
                violations.append("warm replicated run != page oracle")
            if runner._last_data_plane != "mesh":
                violations.append(
                    f"warm run took {runner._last_data_plane}, not the "
                    f"mesh (fallback: {runner.last_mesh_fallback})"
                )
        return int(LAST_RUN_INFO.get("chunks") or 0)

    def make_kill_hook(fault_k):
        """Kill whichever replica serves the run's first chunk — the
        victim is discovered, not hardcoded, so placement order cannot
        unseat the fault. Persistent: a hard-killed replica stays dead
        for the rest of the arm."""
        state = {"victim": None, "fired": 0}

        def hook(k, Ktot):
            rep = mesh_chunk.active_replica()
            if rep is None:
                return
            if state["victim"] is None:
                state["victim"] = rep
            if rep == state["victim"] and k >= fault_k:
                state["fired"] += 1
                raise MeshDeviceLost(
                    f"failover smoke: replica {rep} hard-killed at "
                    f"chunk {k}/{Ktot}"
                )

        return hook, state

    def run_arm(runner, fault_k):
        hook, st = make_kill_hook(fault_k)
        steps0 = METRICS.counter("mesh.chunk_steps")
        compiles0 = METRICS.counter("xla_compiles")
        mesh_chunk.MESH_FAULT_HOOK = hook
        t0 = time.time()
        try:
            rows = runner.execute(RECOVERY_Q).rows
        finally:
            mesh_chunk.MESH_FAULT_HOOK = None
        return {
            "rows": rows,
            "wall": time.time() - t0,
            "fired": st["fired"],
            "victim": st["victim"],
            "steps": int(METRICS.counter("mesh.chunk_steps") - steps0),
            "lowerings": int(METRICS.counter("xla_compiles") - compiles0),
            "plane": runner._last_data_plane,
            "info": dict(LAST_RUN_INFO),
            "rm": runner._replicas.stats() if runner._replicas else {},
        }

    # RESTART arm: no checkpoints — failover lands the sibling at chunk 0
    restart = mk()
    K = warm(restart)
    fault_k = max(1, (3 * K) // 4)
    a_restart = run_arm(restart, fault_k)
    # the victim executed chunks [0, fault_k), the sibling all K: the
    # failover recomputed everything the kill discarded
    re_restart = a_restart["steps"] - K
    if a_restart["rows"] != oracle:
        violations.append("restart arm diverged from the oracle")
    if not a_restart["fired"]:
        violations.append("restart arm: kill never fired")
    elif a_restart["plane"] != "mesh":
        violations.append(
            f"restart arm left the mesh plane ({a_restart['plane']}: "
            f"{restart.last_mesh_fallback})"
        )
    elif a_restart["rm"].get("failovers") != 1:
        violations.append(
            f"restart arm: expected exactly 1 failover "
            f"({a_restart['rm']})"
        )

    # RESUME arm: same kill, checkpoint every 4 chunks — the sibling
    # restores the host-portable checkpoint instead of starting over
    resume = mk(mesh_checkpoint_interval_chunks=4)
    warm(resume)
    a_resume = run_arm(resume, fault_k)
    re_resume = a_resume["steps"] - K
    info = a_resume["info"]
    if a_resume["rows"] != oracle:
        violations.append("resume arm diverged from the oracle")
    if not a_resume["fired"]:
        violations.append("resume arm: kill never fired")
    elif a_resume["plane"] != "mesh":
        violations.append(
            f"resume arm left the mesh plane ({a_resume['plane']}: "
            f"{resume.last_mesh_fallback})"
        )
    elif not info.get("resumes"):
        violations.append(
            f"resume arm: sibling never restored the checkpoint ({info})"
        )
    elif a_resume["rm"].get("failovers") != 1:
        violations.append(
            f"resume arm: expected exactly 1 failover ({a_resume['rm']})"
        )
    if re_resume >= max(re_restart, 1):
        violations.append(
            f"resume arm re-executed {re_resume} chunks — the restart "
            f"arm recomputed {re_restart}; the checkpoint saved nothing"
        )
    if a_resume["wall"] >= a_restart["wall"]:
        violations.append(
            f"resume wall {a_resume['wall']:.2f}s did not beat the "
            f"restart-from-zero wall {a_restart['wall']:.2f}s"
        )
    if a_resume["lowerings"] > 0:
        violations.append(
            f"failover lowered {a_resume['lowerings']} new XLA programs "
            "on the sibling (expected 0: both replicas are warm)"
        )

    # DEADLINE arm: the execution-time limit expires while the sibling
    # is working through the failed-over stretch — the kill must stay
    # typed and name where the run restarted. The hook stalls the
    # sibling (not the victim) past the deadline once a resume has been
    # recorded, so expiry deterministically lands mid-failed-over-chunk.
    deadline_s = 8.0
    resume.session.set_property(
        "query_max_execution_time_s", str(deadline_s)
    )
    hook, st = make_kill_hook(fault_k)
    resumed0 = CHECKPOINTS.resumed
    t_arm = [None]

    def deadline_hook(k, Ktot):
        hook(k, Ktot)
        rep = mesh_chunk.active_replica()
        if (
            rep is not None and st["victim"] is not None
            and rep != st["victim"] and k >= fault_k
            and CHECKPOINTS.resumed > resumed0
        ):
            stall = (t_arm[0] + deadline_s + 0.5) - time.time()
            if stall > 0:
                time.sleep(stall)

    deadline_err = None
    mesh_chunk.MESH_FAULT_HOOK = deadline_hook
    t_arm[0] = time.time()
    try:
        resume.execute(RECOVERY_Q)
        violations.append(
            "deadline arm: query outlived its execution-time limit"
        )
    except ExceededTimeLimitError as e:
        deadline_err = str(e)
    except Exception as e:
        violations.append(
            f"deadline arm: untyped kill {type(e).__name__}: {e}"
        )
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
        resume.session.set_property("query_max_execution_time_s", "0")
    if deadline_err is not None:
        if "[EXCEEDED_TIME_LIMIT]" not in deadline_err:
            violations.append(
                f"deadline arm: kill lost its code ({deadline_err})"
            )
        if "resumed from chunk" not in deadline_err \
                or "on replica" not in deadline_err:
            violations.append(
                f"deadline arm: kill does not name the resume point "
                f"({deadline_err})"
            )

    for v in violations:
        print(f"bench: failover VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "failover_smoke": {
            "devices": n_dev,
            "replicas": 2,
            "chunks": K,
            "fault_chunk": fault_k,
            "resumed_from_chunk": info.get("resumed_from_chunk"),
            "re_executed_restart": re_restart,
            "re_executed_resume": re_resume,
            "restart_wall_s": round(a_restart["wall"], 3),
            "resume_wall_s": round(a_resume["wall"], 3),
            "new_lowerings_on_failover": a_resume["lowerings"],
            "deadline_error": (deadline_err or "")[-120:],
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _multihost_victim() -> int:
    """The victim coordinator of --multihost-smoke: its own process,
    its own 8-device CPU mesh, the survivor's fabric endpoint as its
    only peer. Runs the recovery query with checkpointing every chunk
    (each boundary's snapshot streams to the survivor), then HARD-KILLS
    itself (os._exit — no unwind, no goodbye) at chunk 3K/4 after
    forcing the last snapshot onto the wire."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.fabric import active_fabric

    uri = os.environ["MULTIHOST_FABRIC_URI"]
    runner = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny", mesh_replicas=2,
            mesh_chunk_rows=256, mesh_resume_attempts=0,
            mesh_checkpoint_interval_chunks=1, fabric_peers=uri,
        ),
        n_workers=2, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())

    def hook(k, K):
        fault_k = max(1, (3 * K) // 4)
        if k != fault_k:
            return
        fab = active_fabric()
        if fab is not None:
            # drain the async queue, then ship the LATEST snapshot of
            # every live entry synchronously: the survivor must hold
            # next_chunk == fault_k before this process ceases to exist
            fab.pusher.flush(10.0)
            for key in list(CHECKPOINTS._entries):
                fab.pusher._push(key)
        print(json.dumps(
            {"victim": {"fault_chunk": k, "chunks": K}}
        ), flush=True)
        os._exit(9)

    mesh_chunk.MESH_FAULT_HOOK = hook
    runner.execute(RECOVERY_Q)
    print(json.dumps({"victim": {"error": "fault never fired"}}),
          flush=True)
    return 1


def _multihost_smoke(argv) -> int:
    """--multihost-smoke: CI gate for the multi-host replica fabric
    (trino_tpu/runtime/fabric.py) across a REAL process boundary. Two
    coordinator processes, each over its own 8-device CPU mesh: the
    SURVIVOR warms the recovery query and opens a fabric endpoint over
    its checkpoint store; the VICTIM subprocess attaches that endpoint
    as its fabric peer, checkpoints every chunk (each boundary's bytes
    stream to the survivor), and hard-kills itself (os._exit 9, no
    unwind) at chunk 3K/4. Gates: the pushed snapshot landed in the
    survivor's store across the process boundary; a corrupted replay of
    it (bit-flipped bytes under the original digest) is digest-rejected
    without poisoning the store (fabric.digest_rejects >= 1); the
    survivor's next run of the same query resumes from exactly the
    victim's fault chunk — oracle-equal bytes, zero re-executed
    chunk-steps, zero new XLA lowerings — and beats the survivor's own
    warm full-length wall. Exit 1 on violation."""
    if os.environ.get("MULTIHOST_SMOKE_VICTIM") == "1":
        return _multihost_victim()
    if os.environ.get("MULTIHOST_SMOKE_INNER") != "1":
        env = dict(os.environ)
        env["MULTIHOST_SMOKE_INNER"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--multihost-smoke"],
            env=env,
        ).returncode

    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())

    # both processes authenticate fabric traffic with the same secret
    os.environ.setdefault("TRINO_TPU_INTERNAL_SECRET", "multihost-smoke")

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel.mesh_chunk import LAST_RUN_INFO
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.fabric import HostFabric, checkpoint_digest
    from trino_tpu.runtime.http import FabricClient, FabricServer
    from trino_tpu.runtime.metrics import METRICS

    violations = []
    print(f"bench: multihost smoke ({n_dev}-device cpu mesh per "
          "coordinator, 2 processes, q72-class join, tpch tiny)")

    def mk(**session_kw):
        r = DistributedQueryRunner(
            Session(
                catalog="tpch", schema="tiny", mesh_replicas=2,
                mesh_chunk_rows=256, mesh_resume_attempts=0,
                mesh_checkpoint_interval_chunks=1, **session_kw,
            ),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        return r

    page = mk(mesh_execution=False)
    oracle = page.execute(RECOVERY_Q).rows

    survivor = mk()
    # warm both replicas; the second (fully warm) run's wall is the
    # cold-restart baseline the resume must beat
    wall_cold = None
    for _ in range(2):
        t0 = time.time()
        rows = survivor.execute(RECOVERY_Q).rows
        wall_cold = time.time() - t0
        if rows != oracle:
            violations.append("survivor warm run != page oracle")
        if survivor._last_data_plane != "mesh":
            violations.append(
                f"survivor warm run took {survivor._last_data_plane}, "
                f"not the mesh ({survivor.last_mesh_fallback})"
            )
    K_local = int(LAST_RUN_INFO.get("chunks") or 0)

    # the survivor's fabric endpoint, bound over its LIVE store — what
    # the victim pushes is exactly what resume-on-entry will find
    peer = HostFabric(host_id="survivor")
    srv = FabricServer(peer)
    CHECKPOINTS.clear()  # all entries after the victim dies are pushed ones

    victim_env = dict(os.environ)
    victim_env["MULTIHOST_SMOKE_VICTIM"] = "1"
    victim_env["MULTIHOST_FABRIC_URI"] = srv.uri
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multihost-smoke"],
        env=victim_env, capture_output=True, text=True, timeout=600,
    )
    wall_victim = time.time() - t0
    victim = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                victim = json.loads(line).get("victim", {})
            except ValueError:
                pass
    if proc.returncode != 9:
        violations.append(
            f"victim exited {proc.returncode}, expected the hard-kill 9 "
            f"(stderr tail: {proc.stderr[-300:]!r})"
        )
    fault_k = victim.get("fault_chunk")
    K = victim.get("chunks")
    if not fault_k or not K:
        violations.append(f"victim never reported its fault point ({victim})")
    elif K != K_local:
        violations.append(
            f"chunking diverged across hosts: victim ran {K} chunks, "
            f"survivor {K_local} — checkpoint keys cannot line up"
        )
    if peer.received < 1 or len(CHECKPOINTS) < 1:
        violations.append(
            f"no checkpoint crossed the process boundary "
            f"(received={peer.received}, entries={len(CHECKPOINTS)})"
        )

    # corruption arm: replay the pushed snapshot bit-flipped under its
    # ORIGINAL digest — the digest gate must reject it and leave the
    # genuine entry untouched for the resume arm below
    rejects0 = METRICS.counter("fabric.digest_rejects")
    pushed_key = next(iter(CHECKPOINTS._entries), None)
    if pushed_key is not None:
        data = CHECKPOINTS.export_bytes(pushed_key)
        flipped = bytearray(data)
        flipped[len(flipped) // 2] ^= 0xFF
        client = FabricClient(srv.uri)
        out = client.push_checkpoint(
            pushed_key, bytes(flipped), digest=checkpoint_digest(data)
        )
        if out.get("imported") is not False or (
            out.get("reason") != "digest_mismatch"
        ):
            violations.append(
                f"corrupted payload was not digest-rejected ({out})"
            )
        if METRICS.counter("fabric.digest_rejects") - rejects0 < 1:
            violations.append(
                "fabric.digest_rejects did not count the corrupt replay"
            )
        if CHECKPOINTS.export_bytes(pushed_key) != data:
            violations.append(
                "corrupt replay POISONED the stored checkpoint bytes"
            )

    # resume arm: the survivor re-runs the query; resume-on-entry finds
    # the victim's pushed snapshot in the local store and continues from
    # exactly the fault chunk on warm programs
    steps0 = METRICS.counter("mesh.chunk_steps")
    compiles0 = METRICS.counter("xla_compiles")
    t0 = time.time()
    rows = survivor.execute(RECOVERY_Q).rows
    wall_resume = time.time() - t0
    steps = int(METRICS.counter("mesh.chunk_steps") - steps0)
    new_lowerings = int(METRICS.counter("xla_compiles") - compiles0)
    info = dict(LAST_RUN_INFO)
    if rows != oracle:
        violations.append("survivor resume diverged from the oracle")
    if survivor._last_data_plane != "mesh":
        violations.append(
            f"survivor resume took {survivor._last_data_plane}, not the "
            f"mesh ({survivor.last_mesh_fallback})"
        )
    if not info.get("resumes"):
        violations.append(
            f"survivor never resumed from the pushed checkpoint ({info})"
        )
    elif fault_k and info.get("resumed_from_chunk") != fault_k:
        violations.append(
            f"survivor resumed from chunk {info.get('resumed_from_chunk')}"
            f", not the victim's fault chunk {fault_k} — the last push "
            f"did not make it"
        )
    if fault_k and K and steps != K - fault_k:
        violations.append(
            f"re-executed {steps - (K - fault_k)} chunk-steps "
            f"({steps} steps for {K - fault_k} remaining chunks)"
        )
    if new_lowerings > 0:
        violations.append(
            f"survivor minted {new_lowerings} new XLA lowerings on "
            "resume (expected 0: its programs were already warm)"
        )
    if wall_cold is not None and wall_resume >= wall_cold:
        violations.append(
            f"resume wall {wall_resume:.2f}s did not beat the warm "
            f"full-length wall {wall_cold:.2f}s"
        )

    srv.stop()
    for v in violations:
        print(f"bench: multihost VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "multihost_smoke": {
            "devices": n_dev,
            "chunks": K,
            "fault_chunk": fault_k,
            "victim_exit": proc.returncode,
            "victim_wall_s": round(wall_victim, 3),
            "pushed_entries": peer.received,
            "digest_rejects": int(
                METRICS.counter("fabric.digest_rejects") - rejects0
            ),
            "resumed_from_chunk": info.get("resumed_from_chunk"),
            "re_executed_chunk_steps": (
                steps - (K - fault_k) if fault_k and K else None
            ),
            "new_lowerings_on_resume": new_lowerings,
            "cold_wall_s": round(wall_cold, 3) if wall_cold else None,
            "resume_wall_s": round(wall_resume, 3),
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _preempt_smoke(argv) -> int:
    """--preempt-smoke: CI gate for checkpoint-backed preemptive
    multi-tenancy (trino_tpu/runtime/scheduler.py). One full-width
    8-device CPU mesh is shared by a q72-class analytic and a
    dimension point lookup. Three sections:

    LATENCY: point p99 while the analytic streams chunks must stay
    within 5x the solo point p99 — the fast lane preempts at the next
    chunk boundary instead of queueing behind the whole scan — with
    preemptions >= 1 and every mixed-mode analytic run oracle-equal.

    PARK: a point arrival mid-analytic parks the analytic's device
    carries into the host checkpoint store, the point answers, and the
    analytic resumes from the parked boundary warm. Gates:
    byte-identical rows, executed_chunk_steps == K (zero re-executed
    chunks), parks == 1, zero new XLA lowerings, no parked state left.

    KILL baseline: the same arrival handled the pre-scheduler way —
    abandon the analytic at the same chunk, answer the point, re-run
    the analytic from scratch. The park arm must beat this wall while
    executing fewer chunk-steps. Exit 1 on any violation."""
    if os.environ.get("PREEMPT_SMOKE_INNER") != "1":
        # same clean-slate re-exec as --mesh-smoke: the multi-device
        # host platform must be configured before jax initializes
        env = dict(os.environ)
        env["PREEMPT_SMOKE_INNER"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--preempt-smoke"],
            env=env,
        ).returncode

    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.parallel.mesh_chunk import LAST_RUN_INFO
    from trino_tpu.recovery.checkpoint import CHECKPOINTS
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.metrics import METRICS
    from trino_tpu.runtime.query_tracker import QueryAbandonedError

    POINT_Q = (
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey where n_nationkey = 3"
    )

    def mk(**session_kw):
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny", **session_kw),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        return r

    violations = []
    print(f"bench: preempt smoke ({n_dev}-device cpu mesh, q72-class "
          "analytic vs point lookups, tpch tiny)")
    oracle = mk(mesh_execution=False).execute(RECOVERY_Q).rows

    # periodic checkpointing stays at its default (off): park takes its
    # own exact snapshot at the preempted boundary, so the latency and
    # park arms don't need interval snapshots — and a device_get every
    # chunk boundary would widen the very gaps the point lookups wait on
    r = mk(mesh_chunk_rows=256)
    clean = r.execute(RECOVERY_Q).rows  # warm the analytic programs
    if r._last_data_plane != "mesh":
        violations.append(
            f"clean analytic took {r._last_data_plane}, not the mesh "
            f"(fallback: {r.last_mesh_fallback})"
        )
    if clean != oracle:
        violations.append("clean mesh analytic != page-plane oracle")
    K = int(LAST_RUN_INFO.get("chunks") or 0)
    point_clean = r.execute(POINT_Q).rows  # warm the point programs
    sched = r._mesh_scheduler
    if sched is None:
        violations.append("mesh scheduler never engaged on dispatch")
        for v in violations:
            print(f"bench: preempt VIOLATION: {v}", file=sys.stderr)
        return 1

    # -- KILL baseline: abandon at fault_k, answer, rerun from zero --
    fault_k = max(1, K // 2)
    st_kill = {"fired": 0}

    def abandon_hook(k, Ktot):
        if k == fault_k and not st_kill["fired"]:
            st_kill["fired"] = 1

    steps0 = METRICS.snapshot().get("mesh.chunk_steps", 0.0)
    mesh_chunk.MESH_FAULT_HOOK = abandon_hook
    t0 = time.time()
    try:
        r.execute(RECOVERY_Q, cancel=lambda: bool(st_kill["fired"]))
        violations.append("kill arm: abandoned analytic completed")
    except QueryAbandonedError:
        pass
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    if r.execute(POINT_Q).rows != point_clean:
        violations.append("kill arm: point run diverged")
    # belt and braces: if the abandoned run left any snapshot behind,
    # resubmitting the same statement would warm-resume mid-query —
    # that's the recovery tier helping, not the kill baseline. Drop
    # everything so the rerun honestly starts at zero.
    CHECKPOINTS.clear()
    if r.execute(RECOVERY_Q).rows != oracle:
        violations.append("kill arm: analytic rerun diverged")
    wall_kill = time.time() - t0
    steps_kill = METRICS.snapshot().get("mesh.chunk_steps", 0.0) - steps0
    if not st_kill["fired"]:
        violations.append("kill arm: the abandon hook never fired")

    # -- PARK: same arrival chunk, park/resume instead ----------------
    main_t = threading.current_thread()
    st_park = {"fired": 0, "rows": None, "err": None}

    def point_runner():
        try:
            st_park["rows"] = r.execute(POINT_Q).rows
        except Exception as e:
            st_park["err"] = f"{type(e).__name__}: {e}"

    pth = threading.Thread(target=point_runner, daemon=True)

    def park_hook(k, Ktot):
        # main-thread filter: the point thread's own chunk loop fires
        # this hook too. Holding the boundary until the fast seat is
        # visible makes the NEXT boundary park deterministically.
        if (k == fault_k and not st_park["fired"]
                and threading.current_thread() is main_t):
            st_park["fired"] = 1
            pth.start()
            wait_until = time.time() + 10.0
            while (not sched.waiting_count(fast=True)
                   and time.time() < wait_until):
                time.sleep(0.002)

    s0 = sched.stats()
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    steps1 = METRICS.snapshot().get("mesh.chunk_steps", 0.0)
    mesh_chunk.MESH_FAULT_HOOK = park_hook
    t0 = time.time()
    try:
        rows_park = r.execute(RECOVERY_Q).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    pth.join(timeout=60.0)
    wall_park = time.time() - t0
    steps_park = METRICS.snapshot().get("mesh.chunk_steps", 0.0) - steps1
    new_lowerings = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    info = dict(LAST_RUN_INFO)
    parks = sched.stats()["parks"] - s0["parks"]
    re_exec_park = int(info.get("executed_chunk_steps") or 0) - K
    if not st_park["fired"]:
        violations.append("park arm: the arrival hook never fired")
    if st_park["err"]:
        violations.append(f"park arm: point died: {st_park['err']}")
    elif st_park["rows"] != point_clean:
        violations.append("park arm: point run diverged")
    if rows_park != clean:
        violations.append(
            "park arm: resumed analytic is not byte-identical to the "
            "clean run"
        )
    if parks != 1:
        violations.append(f"park arm: expected exactly 1 park, saw "
                          f"{parks}")
    if re_exec_park != 0:
        violations.append(
            f"park arm re-executed {re_exec_park} chunk-steps "
            "(expected 0: resume is from the parked boundary)"
        )
    if new_lowerings > 0:
        violations.append(
            f"park arm lowered {new_lowerings:g} new XLA programs "
            "(expected 0: parked carries restore onto warm rungs)"
        )
    if CHECKPOINTS.parked_count():
        violations.append(
            f"{CHECKPOINTS.parked_count()} parked snapshots leaked "
            "past resume"
        )
    if wall_park >= wall_kill:
        violations.append(
            f"park wall {wall_park:.2f}s did not beat the "
            f"abandon+rerun wall {wall_kill:.2f}s"
        )
    if steps_park >= steps_kill:
        violations.append(
            f"park arm spent {steps_park:g} chunk-steps vs the kill "
            f"arm's {steps_kill:g} — parking saved nothing"
        )

    # -- LATENCY: solo point p99, then point p99 under the analytic --
    # runs last: the park arm above warmed the park path (first-ever
    # park pays one-time host-buffer costs that would otherwise land on
    # the first mixed sample)
    # 100 mixed samples so p99 is a real percentile (index 98), not the
    # sample max — the mixed tail is one in-flight chunk gap + a park
    # cycle + the point itself (~90-140ms), and a single GIL-jitter
    # outlier shouldn't decide the gate
    solo_reps, mixed_reps = 50, 100

    def p99(walls):
        w = sorted(walls)
        return w[min(len(w) - 1, int(round(0.99 * (len(w) - 1))))]

    solo = []
    for _ in range(solo_reps):
        t0 = time.time()
        rows = r.execute(POINT_Q).rows
        solo.append(time.time() - t0)
        if rows != point_clean:
            violations.append("solo point run diverged")
            break
    p99_solo = p99(solo)

    stop = threading.Event()
    analytic = {"runs": 0, "bad": 0, "err": None}

    def analytic_loop():
        try:
            while not stop.is_set():
                if r.execute(RECOVERY_Q).rows != oracle:
                    analytic["bad"] += 1
                analytic["runs"] += 1
        except Exception as e:  # surfaced as a violation below
            analytic["err"] = f"{type(e).__name__}: {e}"

    pre0 = sched.stats()["preemptions"]
    th = threading.Thread(target=analytic_loop, daemon=True)
    th.start()
    wait_until = time.time() + 5.0
    while sched.holder_query() is None and time.time() < wait_until:
        time.sleep(0.005)  # let the analytic actually hold the mesh
    mixed, streaming = [], []
    for _ in range(mixed_reps):
        # the p99 bound is scoped to arrivals while the analytic holds
        # the mesh (streams chunks) — that's the wait the scheduler
        # owns. Arrivals during the analytic's host planning/feed-build
        # phases contend only for host CPU (the seat is free); they're
        # reported in the overall p99 but not gated
        holder_at_arrival = sched.holder_query() is not None
        t0 = time.time()
        rows = r.execute(POINT_Q).rows
        wall = time.time() - t0
        mixed.append(wall)
        if holder_at_arrival:
            streaming.append(wall)
        if rows != point_clean:
            violations.append("mixed point run diverged")
            break
        time.sleep(0.02)  # hand chunks back to the analytic
    stop.set()
    th.join(timeout=120.0)
    p99_mixed = p99(mixed)
    p99_stream = p99(streaming) if streaming else p99_mixed
    preempts = sched.stats()["preemptions"] - pre0
    if len(streaming) < 10:
        violations.append(
            f"only {len(streaming)} of {len(mixed)} points arrived "
            "while the analytic held the mesh — the mixed window "
            "never really contended"
        )
    if analytic["err"]:
        violations.append(f"mixed analytic died: {analytic['err']}")
    if analytic["bad"]:
        violations.append(
            f"{analytic['bad']} mixed analytic runs diverged from "
            "the oracle"
        )
    if analytic["runs"] < 1:
        violations.append(
            "the analytic made no progress during the mixed window"
        )
    if preempts < 1:
        violations.append(
            "no fast-lane preemption ever fired during mixed traffic"
        )
    if p99_stream > 5.0 * p99_solo:
        violations.append(
            f"streaming-phase point p99 {p99_stream * 1e3:.1f}ms blew "
            f"the 5x solo-p99 bound ({p99_solo * 1e3:.1f}ms solo)"
        )

    for v in violations:
        print(f"bench: preempt VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "preempt_smoke": {
            "devices": n_dev,
            "chunks": K,
            "point_p99_solo_ms": round(p99_solo * 1e3, 2),
            "point_p99_streaming_ms": round(p99_stream * 1e3, 2),
            "point_p99_mixed_overall_ms": round(p99_mixed * 1e3, 2),
            "streaming_samples": len(streaming),
            "slowdown_x": round(p99_stream / max(p99_solo, 1e-9), 2),
            "analytic_runs_during_mixed": analytic["runs"],
            "preemptions_during_mixed": preempts,
            "park_chunk": fault_k + 1,
            "parks": parks,
            "re_executed_chunks_park": re_exec_park,
            "chunk_steps_kill": steps_kill,
            "chunk_steps_park": steps_park,
            "kill_wall_s": round(wall_kill, 3),
            "park_wall_s": round(wall_park, 3),
            "new_lowerings_on_park": new_lowerings,
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _zipf_keys(rng, n: int, n_keys: int, s: float):
    """Seedable zipf-distributed join keys in [0, n_keys): key rank r
    drawn with probability proportional to 1/(r+1)^s. At s=1.4 over 64
    keys the modal key holds ~38% of the rows — past any reasonable
    skew_hot_key_threshold — while staying bounded (np's unbounded
    rng.zipf tail would break fixture determinism across clips)."""
    import numpy as np

    p = 1.0 / np.arange(1, n_keys + 1, dtype=np.float64) ** s
    p /= p.sum()
    return rng.choice(n_keys, size=n, p=p).astype(np.int64)


def _skew_smoke(argv) -> int:
    """--skew-smoke: CI gate for the skew-aware join plane (heavy-hitter
    salted repartition + MXU join-project, ISSUE 16). Two sections over
    seedable zipf key distributions:

    SALTED (8-device cpu mesh): a join whose build side's modal key
    holds ~38% of its rows runs plain, then with adaptive execution +
    skewed_join_salting — the build barrier classifies the heavy hitter
    from OBSERVED stats, annotates the join, and the mesh plane runs
    the exchange salted (hot build rows replicated over all_gather, hot
    probe rows scattered across the all_to_all). Gates: the salted arm
    stays on the mesh, is oracle-equal to the unsalted arm,
    skew.heavy_hitters_detected and skew.salted_exchanges advance, and
    a warm repeat mints zero new XLA lowerings.

    MXU (local path): a high-fanout zipf join feeding SUM/COUNT runs on
    the gather-expansion path, then with mxu_join_enabled — the grouped
    aggregate lowers to the indicator-matmul kernel and the pair batch
    never exists. Gates: oracle-equal, skew.mxu_join_selected advances,
    zero new lowerings on the warm repeat, and the combined skew-aware
    warm wall (salted mesh + MXU local) beats the combined baseline
    warm wall. Exit 1 on any violation."""
    if os.environ.get("SKEW_SMOKE_INNER") != "1":
        # same clean-slate re-exec as --mesh-smoke: the multi-device
        # host platform must be configured before jax initializes
        env = dict(os.environ)
        env["SKEW_SMOKE_INNER"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--skew-smoke"],
            env=env,
        ).returncode

    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())

    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.adaptive import SPOOL
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnMetadata
    from trino_tpu.engine import LocalQueryRunner, Session
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.metrics import METRICS

    def skew_counter(name: str) -> float:
        return METRICS.snapshot().get(f"skew.{name}", 0.0)

    def warm_wall(runner, sql: str, expect) -> tuple:
        """(median-of-3 warm wall, new lowerings over the loop)."""
        walls = []
        compiles0 = METRICS.counter("xla_compiles")
        for _ in range(3):
            t0 = time.time()
            rows = runner.execute(sql).rows
            walls.append(time.time() - t0)
            if rows != expect:
                return None, None
        return (
            sorted(walls)[1],
            METRICS.counter("xla_compiles") - compiles0,
        )

    violations = []
    print(f"bench: skew smoke ({n_dev}-device cpu mesh, zipf keys, "
          "CPU ok)")
    if n_dev < 8:
        violations.append(f"expected an 8-device mesh, got {n_dev}")

    # ---- SALTED section: heavy-hitter detection -> mesh salting ----
    def salted_catalog() -> MemoryConnector:
        conn = MemoryConnector()
        rng = np.random.default_rng(29)
        n, nk = 8000, 64
        conn.load_table(
            "s", "facts",
            [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
            [_zipf_keys(rng, n, nk, 1.4),
             rng.integers(0, 100, n).astype(np.int64)],
        )
        conn.load_table(
            "s", "dim",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
            [_zipf_keys(rng, 2000, nk, 1.4),
             rng.integers(0, 10, 2000).astype(np.int64)],
        )
        return conn

    def mk_mesh(**session_kw):
        r = DistributedQueryRunner(
            Session(
                catalog="memory", schema="s",
                broadcast_join_threshold=0, mesh_chunk_rows=4096,
                **session_kw,
            ),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("memory", salted_catalog())
        return r

    # the partial aggregate above the join is placement-insensitive,
    # so the salted exchange map accepts the plan (a single-step agg
    # grouping ON the join key would rely on key colocation and is
    # correctly refused)
    salt_sql = (
        "select sum(f.v + d.w), count(*) from facts f "
        "join dim d on f.k1 = d.k"
    )
    SPOOL.clear()
    plain = mk_mesh()
    oracle = plain.execute(salt_sql).rows
    if plain._last_data_plane != "mesh":
        violations.append(
            f"unsalted arm ran on {plain._last_data_plane}, not the "
            f"mesh (fallback: {plain.last_mesh_fallback})"
        )
    plain_warm, _ = warm_wall(plain, salt_sql, oracle)
    if plain_warm is None:
        violations.append("unsalted warm repeat diverged")
        plain_warm = 0.0

    salted = mk_mesh(
        adaptive_execution=True, skewed_join_salting=True,
        skew_hot_key_threshold=0.2,
    )
    hh0 = skew_counter("heavy_hitters_detected")
    se0 = skew_counter("salted_exchanges")
    got = salted.execute(salt_sql).rows
    hh = skew_counter("heavy_hitters_detected") - hh0
    se = skew_counter("salted_exchanges") - se0
    if salted._last_data_plane != "mesh":
        violations.append(
            f"salted arm ran on {salted._last_data_plane}, not the "
            f"mesh (fallback: {salted.last_mesh_fallback})"
        )
    if got != oracle:
        violations.append("salted arm != unsalted oracle")
    if hh < 1:
        violations.append(
            "no heavy hitter detected from observed build stats"
        )
    if se < 1:
        violations.append("no exchange ran salted on the mesh")
    salted_warm, salted_lowerings = warm_wall(salted, salt_sql, oracle)
    if salted_warm is None:
        violations.append("salted warm repeat diverged")
        salted_warm = 0.0
    elif salted_lowerings > 0:
        violations.append(
            f"salted warm repeat lowered {salted_lowerings:g} new XLA "
            "programs (expected 0)"
        )

    # ---- MXU section: high-fanout join-project as matmul ----
    def mxu_catalog() -> MemoryConnector:
        conn = MemoryConnector()
        rng = np.random.default_rng(31)
        n, nk, fan = 50_000, 64, 16
        conn.load_table(
            "s", "facts",
            [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
            [_zipf_keys(rng, n, nk, 1.2),
             rng.integers(0, 100, n).astype(np.int64)],
        )
        # uniform fan-out build: every probe row matches `fan` rows, so
        # the gather path expands n*fan pairs the MXU path never builds
        conn.load_table(
            "s", "dim",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("g", T.BIGINT)],
            [np.repeat(np.arange(nk, dtype=np.int64), fan),
             np.arange(nk * fan, dtype=np.int64) % 11],
        )
        return conn

    def mk_local(**session_kw):
        r = LocalQueryRunner(
            Session(catalog="memory", schema="s", **session_kw)
        )
        r.register_catalog("memory", mxu_catalog())
        return r

    mxu_sql = (
        "select d.g, sum(f.v), count(*) from facts f "
        "join dim d on f.k1 = d.k group by d.g order by 1"
    )
    gather = mk_local()
    mxu_oracle = gather.execute(mxu_sql).rows
    gather_warm, _ = warm_wall(gather, mxu_sql, mxu_oracle)
    if gather_warm is None:
        violations.append("gather warm repeat diverged")
        gather_warm = 0.0

    mxu = mk_local(mxu_join_enabled=True, mxu_join_min_work=16.0)
    mj0 = skew_counter("mxu_join_selected")
    mxu_rows = mxu.execute(mxu_sql).rows
    mj = skew_counter("mxu_join_selected") - mj0
    if mxu_rows != mxu_oracle:
        violations.append("MXU arm != gather oracle")
    if mj < 1:
        violations.append("MXU join-project was never selected")
    mxu_warm, mxu_lowerings = warm_wall(mxu, mxu_sql, mxu_oracle)
    if mxu_warm is None:
        violations.append("MXU warm repeat diverged")
        mxu_warm = 0.0
    elif mxu_lowerings > 0:
        violations.append(
            f"MXU warm repeat lowered {mxu_lowerings:g} new XLA "
            "programs (expected 0)"
        )

    # the arm gate: everything-on must beat everything-off on warm
    # walls over the zipf config (the MXU fanout elimination is the
    # CPU-visible win; salting's serialization win needs real shards)
    base_total = plain_warm + gather_warm
    skew_total = salted_warm + mxu_warm
    if skew_total >= base_total:
        violations.append(
            f"skew-aware warm wall {skew_total:.3f}s did not beat the "
            f"baseline {base_total:.3f}s"
        )

    for v in violations:
        print(f"bench: skew VIOLATION: {v}", file=sys.stderr)
    print(json.dumps({
        "skew_smoke": {
            "devices": n_dev,
            "salted": {
                "heavy_hitters_detected": hh,
                "salted_exchanges": se,
                "plain_warm_wall_s": round(plain_warm, 4),
                "salted_warm_wall_s": round(salted_warm, 4),
                "warm_new_lowerings": salted_lowerings,
            },
            "mxu": {
                "selected": mj,
                "gather_warm_wall_s": round(gather_warm, 4),
                "mxu_warm_wall_s": round(mxu_warm, 4),
                "warm_new_lowerings": mxu_lowerings,
            },
            "violations": len(violations),
        }
    }))
    return 1 if violations else 0


def _validate_corpus(argv) -> int:
    """--validate-corpus: CI gate for the plan sanity checkers
    (sql/validate.py). Plans — without executing — every TPC-H and
    TPC-DS-subset query under plan_validation=rules (per-rule
    validation + determinism double-planning), fragments it with
    fragment-level validation, and prints per-checker violation counts
    plus the compile-churn census. Exit 1 on any violation."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.connectors.tpcds import create_tpcds_connector
    from trino_tpu.engine import LocalQueryRunner, Session
    from trino_tpu.sql.fragmenter import plan_distributed
    from trino_tpu.sql.parser import parse
    from trino_tpu.sql.validate import (
        PlanValidationError,
        check_sql_stability,
        collect_subplan_violations,
        collect_violations,
        shape_census,
    )
    from tests.tpch_queries import QUERIES as TPCH_QUERIES
    from tests.test_tpcds import QUERIES as TPCDS_QUERIES

    def make_runner(catalog, create):
        r = LocalQueryRunner(Session(catalog=catalog, schema="tiny"))
        r.register_catalog(catalog, create())
        r.session.plan_validation = "rules"
        return r

    corpora = [
        ("tpch", make_runner("tpch", create_tpch_connector), TPCH_QUERIES),
        ("tpcds", make_runner("tpcds", create_tpcds_connector),
         TPCDS_QUERIES),
    ]
    per_checker: dict = {}
    total_classes = 0
    failures = 0
    t0 = time.time()
    for label, runner, queries in corpora:
        for qid, sql in sorted(queries.items(), key=lambda kv: str(kv[0])):
            name = f"{label} {qid if isinstance(qid, str) else f'q{qid}'}"
            try:
                check_sql_stability(sql, what=name)
                stmt = parse(sql)
                q = stmt.query if hasattr(stmt, "query") else stmt
                # rules mode: per-rule validation + determinism run
                # fire inside _analyze/optimize and raise on violation
                output = runner._analyze(q)
                subplan = plan_distributed(
                    output, runner.catalogs, target_splits=2,
                    validation="off",
                )
            except PlanValidationError as e:
                failures += 1
                per_checker[e.checker] = per_checker.get(e.checker, 0) + 1
                print(f"bench: {name}: VIOLATION {e}", file=sys.stderr)
                continue
            except Exception as e:
                failures += 1
                per_checker["error"] = per_checker.get("error", 0) + 1
                print(f"bench: {name}: ERROR {type(e).__name__}: {e}",
                      file=sys.stderr)
                continue
            # collect-all pass over the final artifacts so one bad plan
            # reports every checker it trips, not just the first
            found = list(collect_violations(output))
            found += list(collect_subplan_violations(subplan))
            for v in found:
                failures += 1
                per_checker[v.checker] = per_checker.get(v.checker, 0) + 1
                print(f"bench: {name}: VIOLATION [{v.checker}] "
                      f"{v.node_path}: {v.message}", file=sys.stderr)
            n_classes = sum(
                len(shape_census(f.root, runner.catalogs))
                for f in subplan.all_fragments()
            )
            total_classes += n_classes
            print(f"bench: {name}: ok "
                  f"fragments={len(subplan.all_fragments())} "
                  f"expected_xla_lowerings={n_classes}")
    checkers = ("refs", "types", "structure", "exchange_keys",
                "determinism", "error")
    print(json.dumps({
        "validate_corpus": {
            "queries": sum(len(q) for _, _, q in corpora),
            "violations": failures,
            "per_checker": {
                c: per_checker.get(c, 0) for c in checkers
            },
            "expected_xla_lowerings_total": total_classes,
            "wall_s": round(time.time() - t0, 2),
        }
    }))
    return 1 if failures else 0


def _analyze(argv) -> int:
    """--analyze: CI gate for the concurrency soundness plane
    (trino_tpu/analysis/). Statically scans every module in the package
    for lock-order cycles, guarded_by violations, unlocked writes to
    module-level mutable globals, condition-waits while holding another
    lock, non-reentrant re-entry, and thread spawns that bypass the
    registry. Exit 1 on any finding."""
    from trino_tpu.analysis import analyze_package

    t0 = time.time()
    rep = analyze_package()
    for f in rep.findings:
        print(f"bench: ANALYZE-VIOLATION [{f.kind}] {f.file}:{f.line}: "
              f"{f.message}", file=sys.stderr)
    summary = rep.summary()
    summary["wall_s"] = round(time.time() - t0, 2)
    print(json.dumps({"analyze": summary}))
    return 0 if rep.ok else 1


def main() -> None:
    if "--serve-smoke" in sys.argv:
        sys.exit(_serve_smoke(sys.argv))
    if "--serve" in sys.argv:
        sys.exit(_serve(sys.argv))
    if "--chaos-smoke" in sys.argv:
        sys.exit(_chaos_smoke(sys.argv))
    if "--warmup-smoke" in sys.argv:
        sys.exit(_warmup_smoke(sys.argv))
    if "--trace-smoke" in sys.argv:
        sys.exit(_trace_smoke(sys.argv))
    if "--mesh-smoke" in sys.argv:
        sys.exit(_mesh_smoke(sys.argv))
    if "--resident-smoke" in sys.argv:
        sys.exit(_resident_smoke(sys.argv))
    if "--adaptive-smoke" in sys.argv:
        sys.exit(_adaptive_smoke(sys.argv))
    if "--recovery-smoke" in sys.argv:
        sys.exit(_recovery_smoke(sys.argv))
    if "--failover-smoke" in sys.argv:
        sys.exit(_failover_smoke(sys.argv))
    if "--skew-smoke" in sys.argv:
        sys.exit(_skew_smoke(sys.argv))
    if "--multihost-smoke" in sys.argv:
        sys.exit(_multihost_smoke(sys.argv))
    if "--preempt-smoke" in sys.argv:
        sys.exit(_preempt_smoke(sys.argv))
    if "--validate-corpus" in sys.argv:
        sys.exit(_validate_corpus(sys.argv))
    if "--analyze" in sys.argv:
        sys.exit(_analyze(sys.argv))
    if os.environ.get("BENCH_INNER") == "1":
        import jax

        # This environment injects a sitecustomize that imports jax with
        # JAX_PLATFORMS pinned to the TPU plugin before bench.py runs, so
        # the env var alone cannot demote a child to CPU — the config
        # update below (legal until a backend initializes) is what makes
        # the "CPU baseline" subprocess actually run on CPU.
        plat = os.environ.get("BENCH_PLATFORM")
        if plat:
            jax.config.update("jax_platforms", plat)
        rec = run_benches()
        rec["_platform"] = jax.devices()[0].platform
        print(json.dumps(rec))
        return

    t_start = time.time()
    # the driver applies its own outer timeout and the incremental
    # emission keeps the last stdout line parseable whenever the kill
    # lands — so the self-deadline is generous and merely orders work
    # (device configs before CPU baselines, SF-large baselines last)
    deadline = float(os.environ.get("BENCH_DEADLINE", "2700"))
    cfg_timeout = int(os.environ.get("BENCH_CONFIG_TIMEOUT", "1800"))
    cpu_timeout = int(os.environ.get("BENCH_CPU_TIMEOUT", "1800"))
    skip_cpu = os.environ.get("BENCH_SKIP_CPU") == "1"

    def remaining() -> float:
        return deadline - (time.time() - t_start)

    device: dict = {}
    baseline: dict = {}
    cached = _load_cached_baselines()
    gbs = None
    platform = None
    _emit(device, baseline, gbs, cached)  # parseable line from the start

    # fail fast on a dead backend: one bounded preflight, then either
    # proceed or emit an explicit device_unavailable record carrying
    # the last committed dev-loop walls (BENCH_DEV.json) so the round
    # still ships machine-readable device numbers
    pf_timeouts = [
        int(x) for x in
        os.environ.get("BENCH_PREFLIGHT_TIMEOUTS", "45,75").split(",")
    ]
    pf_platform, pf_tail = _preflight_device(pf_timeouts)
    # --watch [seconds]: dev-loop mode — keep re-running the preflight
    # on an interval until a real device comes up, then fall through to
    # one full bench (whose walls land in BENCH_DEV.json via
    # record_bench_dev as usual)
    if "--watch" in sys.argv:
        i = sys.argv.index("--watch")
        try:
            watch_s = float(sys.argv[i + 1])
        except (IndexError, ValueError):
            watch_s = 300.0
        while pf_platform in (None, "cpu"):
            why = "backend init failed" if pf_platform is None else "cpu only"
            print(
                f"bench: watch — no device ({why}); retry in {watch_s:g}s",
                file=sys.stderr, flush=True,
            )
            time.sleep(watch_s)
            pf_platform, pf_tail = _preflight_device(pf_timeouts)
        print(
            f"bench: watch — device up ({pf_platform}); running full bench",
            file=sys.stderr, flush=True,
        )
        t_start = time.time()  # the wait does not count against the deadline
    if pf_platform is None:
        dev_walls = latest_dev_walls()
        print(
            json.dumps(
                {
                    "metric": "device_unavailable",
                    "value": 0.0,
                    "unit": "s",
                    "vs_baseline": 0.0,
                    "extra": {
                        "diagnostics": pf_tail,
                        "last_dev_walls": dev_walls,
                        "note": (
                            "backend init failed preflight; walls are the "
                            "newest committed dev-loop device measurements"
                        ),
                    },
                }
            ),
            flush=True,
        )
        return

    # device configs run as subprocesses BEFORE this process touches
    # jax: a parent holding the TPU could wedge children on
    # device-exclusive backends
    cfgs = _configs()
    for name, sf in cfgs:
        key = f"{name}_sf{sf:g}"
        budget = min(cfg_timeout, remaining() - 20)
        if budget < 60:
            print(f"bench: deadline — skipping {key} and later configs",
                  file=sys.stderr, flush=True)
            break
        secs, plat = _run_one_subprocess(name, sf, {}, int(budget))
        if secs is not None:
            device[key] = secs
            platform = plat or platform
            if platform not in (None, "cpu"):
                record_bench_dev(key, secs, platform)
            _emit(device, baseline, gbs, cached)
        # small-SF CPU baselines interleave right behind their device
        # run — they are cheap and give the headline a measured
        # vs_baseline as early as possible. SF-large baselines wait
        # until every device config has had its shot (skipped first).
        if (secs is not None and sf <= 1 and platform not in (None, "cpu")
                and not skip_cpu):
            budget = min(cpu_timeout, remaining() - 20)
            if budget >= 60:
                b, _ = _run_one_subprocess(
                    name, sf, _CPU_ENV,
                    int(budget),
                )
                if b is not None:
                    baseline[key] = b
                    _save_cached_baseline(key, b)
                    _emit(device, baseline, gbs, cached)

    # probe throughput (parent imports jax here — device children done)
    if platform not in (None, "cpu") and remaining() > 60:
        try:
            gbs = probe_gbs()
            record_bench_dev("probe_gbs", gbs, platform or "device",
                             note="unit GB/s, not seconds")
            _emit(device, baseline, gbs, cached)
        except Exception as ex:
            print(f"bench: probe_gbs skipped ({type(ex).__name__})",
                  file=sys.stderr, flush=True)

    # SF-large CPU baselines last: first to go when budget runs short
    if platform not in (None, "cpu") and not skip_cpu:
        for name, sf in cfgs:
            key = f"{name}_sf{sf:g}"
            if sf <= 1 or key not in device or key in baseline:
                continue
            budget = min(cpu_timeout, remaining() - 20)
            if budget < 120:
                print(f"bench: deadline — skipping cpu baseline for {key}",
                      file=sys.stderr, flush=True)
                continue
            b, _ = _run_one_subprocess(
                name, sf, _CPU_ENV,
                int(budget),
            )
            if b is not None:
                baseline[key] = b
                _save_cached_baseline(key, b)
                _emit(device, baseline, gbs, cached)

    _emit(device, baseline, gbs, cached)


if __name__ == "__main__":
    main()
